"""GPipe-style pipeline parallelism over a `pp` mesh axis.

The reference's model-parallel backend pipelines NeMo/Megatron stages
across nodes (ref: configs/nemo_configs/megatron_20b.yaml
`pipeline_model_parallel_size`, trainer/nemo_ppo_trainer.py) with
point-to-point sends choreographed by Megatron's schedules. The TPU
analogue here exploits the repo's scan-stacked layer layout: layer
params already live in one array with a leading `n_layer` axis, so a
pipeline stage is just a shard of that axis.

Mechanics (microbatch pipelining, the classic GPipe schedule):
- `jax.shard_map` manual over ONLY the `pp` axis (`axis_names={"pp"}`)
  — dp/fsdp/tp stay under GSPMD, so FSDP gathers and tensor-parallel
  all-reduces compose with pipelining without manual collectives.
- Each stage holds `n_layer/pp` consecutive layers (its slice of the
  stacked params). The batch is split into M microbatches; a scan runs
  M + pp - 1 ticks. Per tick every stage applies its layers to one
  microbatch and `ppermute`s the activation to the next stage — a
  neighbor-to-neighbor ICI hop, the cheapest collective on the torus.
- Stage 0 feeds fresh microbatches; the last stage accumulates outputs,
  broadcast back with a masked `psum` (zeros elsewhere) so downstream
  ops (final norm, logits) run under plain GSPMD again.
- Hydra/value-branch captures (hidden entering layer g) accumulate on
  whichever stage owns layer g via a one-hot mask inside the stage scan
  and merge in the same masked-psum step.

The bubble fraction is (pp-1)/(M+pp-1): raise `pp_microbatches` to
amortize. Two backward schedules (`pp_schedule`): "gpipe" (default)
differentiates the forward scan — the transpose runs reverse-direction
permutes but stores one boundary activation per TICK, O(M+pp) of them;
"1f1b" (`_run_1f1b`) is a custom VJP whose backward interleaves a
recompute pipeline with the cotangent pipeline so per-stage boundary
liveness is O(pp), at the price of one extra forward. `remat` composes
with either: it checkpoints each layer body so per-layer activations
inside a stage are recomputed rather than stored.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Array = jax.Array


def pp_microbatch_count(
    mesh,
    n_layer: int,
    batch: int,
    pp_microbatches: int = 0,
    stacklevel: int = 4,
) -> int:
    """Shared trace-time pp gate: the microbatch count to pipeline a
    stack with, or 0 for the sequential scan. One definition so the
    causal and seq2seq models cannot drift on eligibility rules, and so
    the divisibility check guards the exact value `pipelined_layers`
    receives."""
    if mesh is None:
        return 0
    m = dict(mesh.shape)
    pp = m.get("pp", 1)
    if pp <= 1:
        return 0
    if m.get("sp", 1) > 1:
        raise ValueError(
            "pp and sp are mutually exclusive: ring attention shards the "
            f"sequence inside each layer, pipelining shards the layers (mesh {m})"
        )
    n_mb = pp_microbatches or pp
    if n_layer % pp or batch % n_mb:
        import warnings

        warnings.warn(
            f"pipeline parallelism requested (pp={pp}) but n_layer={n_layer} "
            f"or batch={batch} don't divide (microbatches={n_mb}); falling "
            "back to the sequential scan",
            stacklevel=stacklevel,
        )
        return 0
    return n_mb


def _microbatch_flags(tree, batch: int):
    """Static per-leaf decision: leaves with leading dim == batch get
    split per microbatch; broadcast-shaped aux (e.g. [1, 1, T, S] biases)
    is passed whole to every layer call."""
    return jax.tree_util.tree_map(
        lambda x: jnp.ndim(x) > 0 and x.shape[0] == batch, tree
    )


def _split_microbatches(tree, flags, n_mb: int):
    return jax.tree_util.tree_map(
        lambda x, f: x.reshape((n_mb, x.shape[0] // n_mb) + x.shape[1:]) if f else x,
        tree,
        flags,
    )


def _index_microbatch(tree, flags, m: Array):
    return jax.tree_util.tree_map(
        lambda x, f: x[m] if f else x, tree, flags
    )


def _partition_diff(tree):
    """Split a pytree into (diff_leaves, aux_leaves, rebuild): inexact
    leaves can carry gradients; integer leaves (layer indices, positions,
    key masks) ride along as non-differentiable aux."""
    leaves, tdef = jax.tree_util.tree_flatten(tree)
    is_diff = [jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact) for l in leaves]
    diff = [l for l, d in zip(leaves, is_diff) if d]
    aux = [l for l, d in zip(leaves, is_diff) if not d]

    def rebuild(diff_leaves, aux_leaves):
        di, ai = iter(diff_leaves), iter(aux_leaves)
        return tdef.unflatten([next(di) if d else next(ai) for d in is_diff])

    return diff, aux, rebuild


def pipelined_layers(
    mesh: Mesh,
    layer_apply: Callable[[Dict, Array, Any], Array],
    xs: Dict,
    h: Array,
    ctx: Any,
    *,
    n_microbatch: int,
    capture_points: Sequence[int] = (),
    remat: bool = False,
    schedule: str = "gpipe",
) -> Tuple[Array, Tuple[Array, ...]]:
    """Run L stacked layers over the mesh's `pp` axis, pipelined.

    Args:
      layer_apply: (layer_xs_slice, h, ctx_microbatch) -> h for ONE layer.
      xs: pytree whose every leaf has leading axis L (stacked layer
        params + any per-layer scalars). L must divide by mesh pp size.
      h: [B, ...] activations entering layer 0. B must divide by
        n_microbatch (and B/n_microbatch by dp*fsdp for good layouts).
      ctx: pytree of batch-shaped aux inputs (attention bias, positions,
        key masks). Leaves with leading dim B are split per microbatch;
        other leaves are passed whole to every layer call.
      capture_points: global layer indices g; returns the hidden state
        ENTERING layer g for each (the hydra/value-branch fork inputs).
      schedule: "gpipe" differentiates through the forward scan — simple,
        but the scan transpose stores one boundary activation per TICK
        (M + pp - 1 of them). "1f1b" runs the same forward under a
        custom VJP whose backward interleaves a recompute pipeline with
        the cotangent pipeline (the 1F1B idea: a microbatch's backward
        starts as soon as its forward reaches the last stage), holding a
        rolling buffer of at most 2*pp - 1 boundary activations per
        stage and accumulating weight grads stage-locally across
        microbatches. Cost: the backward re-runs each stage forward
        TWICE (once in the recompute wavefront to regenerate boundary
        inputs, once as the VJP primal) — one forward more than
        gpipe+remat — in exchange for O(pp) instead of O(M) boundary
        memory. Pick it when microbatch count, not FLOPs, is the
        binding constraint (deep DCN meshes with many microbatches).
        Parity: NeMo/Apex interleaved schedules, ref
        modeling_nemo_ppo.py:573-585,713-731.

    Returns (h_out [B, ...], captures tuple aligned with capture_points).
    """
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"pp_schedule={schedule!r} not in ('gpipe', '1f1b')")
    n_stages = mesh.shape["pp"]
    leaves = jax.tree_util.tree_leaves(xs)
    n_layer = leaves[0].shape[0]
    if n_layer % n_stages:
        raise ValueError(
            f"n_layer={n_layer} not divisible by pp={n_stages}"
        )
    B = h.shape[0]
    M = n_microbatch
    if B % M:
        raise ValueError(f"batch {B} not divisible by pp microbatches {M}")
    points = tuple(capture_points)
    n_pts = len(points)
    # XLA's CPU backend crashes (AllReducePromotion CHECK) on bf16
    # all-reduces, which both the masked-psum broadcast and the shard_map
    # transpose of replicated inputs emit. Carry boundary activations in
    # f32 on CPU meshes: bf16<->f32 round-trips are bit-exact, so the
    # numerics match the sequential scan; TPU keeps bf16 on the wire.
    compute_dtype = h.dtype
    on_cpu = mesh.devices.flat[0].platform == "cpu"
    io_dtype = (
        jnp.float32 if (on_cpu and compute_dtype == jnp.bfloat16) else compute_dtype
    )

    xs = dict(xs, __g__=jnp.arange(n_layer))  # global layer index per slice row

    def stage(xs_local, h, ctx_mb):
        """Apply this stage's layer slice; accumulate capture hiddens."""

        def body(carry, layer):
            h, caps = carry
            if n_pts:
                g = layer["__g__"]
                onehot = jnp.stack(
                    [(g == p).astype(caps.dtype) for p in points]
                ).reshape((n_pts,) + (1,) * h.ndim)
                caps = caps + onehot * h[None].astype(caps.dtype)
            h = layer_apply(
                {k: v for k, v in layer.items() if k != "__g__"}, h, ctx_mb
            )
            return (h, caps), None

        from trlx_tpu.ops.remat import wrap_remat

        body = wrap_remat(body, remat)
        caps0 = jnp.zeros((n_pts,) + h.shape, io_dtype)
        (h, caps), _ = jax.lax.scan(body, (h.astype(compute_dtype), caps0), xs_local)
        return h.astype(io_dtype), caps

    def pipelined(xs_local, h_mb, ctx_mb):
        s = jax.lax.axis_index("pp")
        last = n_stages - 1
        buf = jnp.zeros_like(h_mb[0])
        outs = jnp.zeros_like(h_mb)
        caps_store = jnp.zeros((M, n_pts) + h_mb.shape[1:], h_mb.dtype)

        def tick(carry, t):
            buf, outs, caps_store = carry
            # stage s works on microbatch t - s this tick (GPipe schedule)
            m = t - s
            m_c = jnp.clip(m, 0, M - 1)
            valid = (m >= 0) & (m < M)
            # restore boundary-promoted ctx leaves to their compute dtype
            # (bf16<->f32 round-trips are bit-exact)
            ctx_t = restore_ctx(_index_microbatch(ctx_mb, ctx_flags, m_c))
            h_in = jnp.where(s == 0, h_mb[jnp.clip(t, 0, M - 1)], buf)
            y, caps = stage(xs_local, h_in, ctx_t)
            if n_pts:
                caps_store = caps_store.at[m_c].add(
                    jnp.where(valid, caps, jnp.zeros_like(caps))
                )
            outs = outs.at[m_c].add(
                jnp.where(valid & (s == last), y, jnp.zeros_like(y))
            )
            buf = jax.lax.ppermute(y, "pp", perm_up)
            return (buf, outs, caps_store), None

        (buf, outs, caps_store), _ = jax.lax.scan(
            tick, (buf, outs, caps_store), jnp.arange(M + n_stages - 1)
        )
        # only the last stage holds real outputs / the owning stage holds
        # each capture; masked psum broadcasts both to every pp rank
        outs = jax.lax.psum(outs, "pp")
        caps_store = jax.lax.psum(caps_store, "pp")
        return outs, caps_store

    h_mb = h.reshape((M, B // M) + h.shape[1:]).astype(io_dtype)
    # keep microbatch rows spread over the data axes, not gathered onto pp
    h_mb = jax.lax.with_sharding_constraint(
        h_mb, NamedSharding(mesh, P(None, ("dp", "fsdp")))
    )
    ctx_flags = _microbatch_flags(ctx, B)
    # the bf16-all-reduce CPU workaround applies to ctx leaves too: the
    # shard_map transpose of a replicated-in bf16 leaf (e.g. a T5
    # encoder_hidden) emits a bf16 psum over pp for its cotangent
    ctx_dtypes = jax.tree_util.tree_map(lambda x: x.dtype, ctx)
    if on_cpu:
        ctx = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x,
            ctx,
        )
    ctx_mb = _split_microbatches(ctx, ctx_flags, M)

    # one definition each for the fwd schedule AND the 1f1b backward, so
    # the dtype-restore and neighbor-hop wiring can't drift between them
    def restore_ctx(ct):
        return jax.tree_util.tree_map(
            lambda x, d: x.astype(d) if x.dtype != d else x, ct, ctx_dtypes
        )

    perm_up = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    perm_dn = [(i, (i - 1) % n_stages) for i in range(n_stages)]

    f = jax.shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(P("pp"), P(), P()),
        out_specs=(P(), P()),
        axis_names={"pp"},
        check_vma=False,
    )
    if schedule == "1f1b":
        outs, caps_store = _run_1f1b(
            mesh, f, stage, xs, h_mb, ctx_mb, ctx_flags, restore_ctx,
            M=M, n_stages=n_stages, perm_up=perm_up, perm_dn=perm_dn,
        )
    else:
        outs, caps_store = f(xs, h_mb, ctx_mb)
    h_out = outs.reshape((B,) + h.shape[1:]).astype(compute_dtype)
    # caps_store: [M, n_pts, B/M, ...] -> per point [B, ...]
    captures = tuple(
        jnp.moveaxis(caps_store, 1, 0)[i]
        .reshape((B,) + h.shape[1:])
        .astype(compute_dtype)
        for i in range(n_pts)
    )
    return h_out, captures


def _run_1f1b(mesh, fwd, stage, xs, h_mb, ctx_mb, ctx_flags, restore_ctx,
              *, M: int, n_stages: int, perm_up, perm_dn):
    """The 1F1B memory-bounded differentiation of the pipelined region.

    Forward: the ordinary GPipe shard_map (`fwd`), under a custom VJP
    that saves ONLY the region inputs. Backward: one shard_map scan
    interleaving two wavefronts per tick —

      recompute   mb r = t - s flows stage 0 -> pp-1 (the forward
                  schedule re-run), each stage pushing the activation
                  that ENTERED it into a rolling ring of 2*pp-1 slots;
      cotangent   mb b = t - 2(pp-1) + s flows stage pp-1 -> 0; the
                  stage pops h_in(b) from its ring (pushed exactly
                  2(pp-1-s) ticks earlier — the 1F1B property: a
                  microbatch's backward launches the moment its forward
                  reaches the last stage, so per-stage liveness is
                  O(pp), not O(M)), runs its local VJP, accumulates its
                  layer-slice weight grads in place, and ppermutes the
                  input cotangent to the previous stage.

    Capture cotangents inject automatically: the stage VJP is taken on
    (h_out, caps) jointly, and caps depends on h only at the owning
    stage. Integer leaves (layer indices, positions, key masks) ride as
    non-differentiable aux and get float0 cotangents at the boundary.

    FLOPs: the backward runs each stage forward twice per microbatch
    (recompute wavefront + VJP primal; the two operate on DIFFERENT
    microbatches at any tick, so they cannot be shared) — one extra
    forward versus gpipe+remat. Storing VJP residuals in the ring
    instead would erase the extra forward at O(pp)×stage-activation
    memory (torch 1F1B's layout), but residual closures cannot ride a
    lax.scan carry; boundary-only storage is the compiler-friendly
    trade.
    """
    last = n_stages - 1
    ring_slots = 2 * last + 1
    n_ticks = M + 2 * last

    @jax.custom_vjp
    def run(xs_, h_mb_, ctx_mb_):
        return fwd(xs_, h_mb_, ctx_mb_)

    def run_fwd(xs_, h_mb_, ctx_mb_):
        return fwd(xs_, h_mb_, ctx_mb_), (xs_, h_mb_, ctx_mb_)

    def run_bwd(res, cts):
        xs_, h_mb_, ctx_mb_ = res
        d_outs, d_caps = cts

        # diff/aux layout is identical globally and per-shard (sharding
        # never changes tree structure), so these also describe xs_local
        _, xs_aux_g, rebuild_xs_g = _partition_diff(xs_)
        ctx_leaves_g, ctx_tdef = jax.tree_util.tree_flatten(ctx_mb_)
        ctx_is_diff = [
            jnp.issubdtype(l.dtype, jnp.inexact) for l in ctx_leaves_g
        ]
        flag_leaves = jax.tree_util.tree_leaves(ctx_flags)
        dctx_split = [f for f, d in zip(flag_leaves, ctx_is_diff) if d]

        def bwd_shard(xs_local, h_loc, ctx_loc, douts, dcaps):
            s = jax.lax.axis_index("pp")
            xs_diff, xs_aux, rebuild_xs = _partition_diff(xs_local)
            ctx_leaves = jax.tree_util.tree_leaves(ctx_loc)

            def ctx_at(m):
                return _index_microbatch(ctx_loc, ctx_flags, m)

            mb_shape = h_loc.shape[1:]

            def tick(carry, t):
                ring, rec_buf, cot_buf, gxs, dh_store, dctx = carry
                # recompute wavefront (forward schedule re-run)
                r = t - s
                ctx_r = restore_ctx(ctx_at(jnp.clip(r, 0, M - 1)))
                h_in_rec = jnp.where(
                    s == 0, h_loc[jnp.clip(t, 0, M - 1)], rec_buf
                )
                y, _ = stage(xs_local, h_in_rec, ctx_r)
                ring = jax.lax.dynamic_update_index_in_dim(
                    ring, h_in_rec, jnp.mod(t, ring_slots), 0
                )
                rec_next = jax.lax.ppermute(y, "pp", perm_up)

                # cotangent wavefront
                b = t - 2 * last + s
                b_c = jnp.clip(b, 0, M - 1)
                h_in_b = jax.lax.dynamic_index_in_dim(
                    ring, jnp.mod(b_c + s, ring_slots), 0, keepdims=False
                )
                ctx_b = ctx_at(b_c)
                cb_diff, cb_aux, rebuild_cb = _partition_diff(ctx_b)

                def f(xd, h_, cd):
                    return stage(
                        rebuild_xs(xd, xs_aux), h_,
                        restore_ctx(rebuild_cb(cd, cb_aux)),
                    )

                _, vjp_fn = jax.vjp(f, xs_diff, h_in_b, cb_diff)
                g_h = jnp.where(s == last, douts[b_c], cot_buf)
                d_xs, d_h, d_ctx = vjp_fn((g_h, dcaps[b_c]))
                valid = (b >= 0) & (b < M)
                vsel = lambda d: jnp.where(valid, d, jnp.zeros_like(d))
                gxs = [a + vsel(d) for a, d in zip(gxs, d_xs)]
                dh_store = dh_store.at[b_c].add(
                    jnp.where(valid & (s == 0), d_h, jnp.zeros_like(d_h))
                )
                dctx = [
                    a.at[b_c].add(vsel(d)) if split else a + vsel(d)
                    for a, d, split in zip(dctx, d_ctx, dctx_split)
                ]
                cot_next = jax.lax.ppermute(d_h, "pp", perm_dn)
                return (ring, rec_next, cot_next, gxs, dh_store, dctx), None

            carry0 = (
                jnp.zeros((ring_slots,) + mb_shape, h_loc.dtype),
                jnp.zeros(mb_shape, h_loc.dtype),
                jnp.zeros(mb_shape, h_loc.dtype),
                [jnp.zeros_like(l) for l in xs_diff],
                jnp.zeros_like(h_loc),
                [
                    jnp.zeros_like(l)
                    for l, d in zip(ctx_leaves, ctx_is_diff) if d
                ],
            )
            (_, _, _, gxs, dh_store, dctx), _ = jax.lax.scan(
                tick, carry0, jnp.arange(n_ticks)
            )
            # weight grads are stage-local (their slice of the stacked
            # axis); boundary/ctx cotangents merge across stages
            dh_store = jax.lax.psum(dh_store, "pp")
            dctx = [jax.lax.psum(a, "pp") for a in dctx]
            return gxs, dh_store, dctx

        n_xd = len(_partition_diff(xs_)[0])
        n_cd = sum(ctx_is_diff)
        g = jax.shard_map(
            bwd_shard,
            mesh=mesh,
            in_specs=(P("pp"), P(), P(), P(), P()),
            out_specs=([P("pp")] * n_xd, P(), [P()] * n_cd),
            axis_names={"pp"},
            check_vma=False,
        )
        import numpy as np

        gxs, dh_mb, dctx = g(xs_, h_mb_, ctx_mb_, d_outs, d_caps)
        dxs = rebuild_xs_g(
            gxs, [np.zeros(jnp.shape(a), jax.dtypes.float0) for a in xs_aux_g]
        )
        it = iter(dctx)
        dctx_full = ctx_tdef.unflatten([
            next(it) if d else np.zeros(l.shape, jax.dtypes.float0)
            for l, d in zip(ctx_leaves_g, ctx_is_diff)
        ])
        return dxs, dh_mb, dctx_full

    run.defvjp(run_fwd, run_bwd)
    return run(xs, h_mb, ctx_mb)
