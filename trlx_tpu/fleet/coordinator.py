"""Learner-side fleet coordinator: dispatch, collect, degrade.

Sits between the PPO trainer's experience-transport loop and the
cross-process worker fleet. The trainer keeps owning the transport
lease for every chunk; the coordinator turns "produce this chunk" into
a dispatch message a registered worker executes, watches the worker's
membership heartbeats while it runs, and hands the delivered payload
back. A silent worker is evicted (flap-tracked, quarantined past
``fleet.flap_limit``) and the chunk re-dispatched with the SAME replay
snapshot — regeneration is bit-identical, so a worker death is
invisible in the consumed stream. When the live fleet falls below
``fleet.min_workers`` the coordinator reports DEGRADED and the trainer
falls back to in-process production (the ``fleet`` guardrail signal
trips once per transition).

Chunk messaging rides the pluggable transport (``exp/net.py``). On the
default shared-fs backend the layout under the fleet dir is the
original atomic-rename protocol, byte for byte::

    dispatch/e{epoch}_s{seq}_a{attempt}/   assignment for one worker
    chunks/e{epoch}_s{seq}/                the delivered chunk payload

On the tcp backend the same (topic, name) messages live in a
:class:`trlx_tpu.exp.net.TcpHub`, and the CONTROL PLANE — membership
records, the shutdown flag, the chunked weight broadcast — rides the
very same transport, so workers need no shared filesystem at all.

Delivery is naturally deduplicating: the chunk dir name carries no
attempt, so whichever attempt's rename lands first wins and the other
drops itself (both are bit-identical by the replay contract anyway).

Transport failures DEGRADE instead of crash: ``dispatch`` reports
False, polls read as not-yet-delivered, and the trainer's existing
below-min-workers ladder takes over (in-process fallback is
bit-identical by the replay contract). A learner-side chaos
``hub_crash`` relaunches the hub empty via :meth:`FleetCoordinator.
crash_hub` — recovery is re-registration (worker beats), fresh
dispatch attempts, and the put dedup for re-posted in-flight traffic.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from trlx_tpu.fleet.broadcast import BROADCAST_TOPIC, make_broadcast
from trlx_tpu.fleet.config import FleetConfig
from trlx_tpu.fleet.membership import MEMBERSHIP_RECORD, WorkerRegistry
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

DISPATCH_DIR = "dispatch"
CHUNKS_DIR = "chunks"
BROADCAST_DIR = BROADCAST_TOPIC


def chunk_name(chunk_id: Tuple[int, int]) -> str:
    return f"e{int(chunk_id[0])}_s{int(chunk_id[1])}"


class FleetCoordinator:
    def __init__(
        self,
        cfg: FleetConfig,
        root: str,
        owner: str = "learner",
        clock: Callable[[], float] = time.time,
        transport=None,
    ):
        from trlx_tpu.exp.net import (
            SharedFSTransport,
            base_transport,
            make_server_transport,
        )

        self.cfg = cfg
        self.root = root
        self._clock = clock
        # everything — chunk dispatch/delivery, membership records,
        # weight broadcast — rides the pluggable transport; the default
        # shared-fs backend reproduces the pre-interface layout byte
        # for byte. On the tcp backend the LEARNER hosts the hub
        # (workers connect with the same spec's host/port) unless
        # ``host_hub: false`` points at an external supervised hub.
        self.hub = None
        if transport is not None:
            self.transport = transport
            self.transport_spec = None  # caller-supplied: unknown wire
        else:
            self.hub, self.transport, self.transport_spec = (
                make_server_transport(cfg.transport, root)
            )
        shared_fs = isinstance(
            base_transport(self.transport), SharedFSTransport
        )
        if shared_fs:
            # golden layout only: a tcp-only learner must leave no
            # fleet directories behind (proof the workers never need
            # a shared path)
            os.makedirs(os.path.join(root, DISPATCH_DIR), exist_ok=True)
            os.makedirs(os.path.join(root, CHUNKS_DIR), exist_ok=True)
        self.registry = WorkerRegistry(
            root if shared_fs else self.transport,
            worker_ttl_s=cfg.worker_ttl_s,
            flap_limit=cfg.flap_limit,
            flap_backoff_s=cfg.flap_backoff_s,
            clock=clock,
        )
        self.broadcast = make_broadcast(
            self.transport, keep=cfg.broadcast_keep
        )
        # the attach handshake: bump the membership epoch so surviving
        # workers from a previous learner incarnation re-register
        self.membership_epoch = self.registry.open_epoch(owner)
        self.degraded = False
        self._waited_startup = False
        self._published_version: Optional[int] = None
        self._rr = 0  # round-robin cursor over the live set
        # per-chunk dispatch-attempt counter: every dispatch (first try,
        # eviction re-dispatch, staleness regeneration) gets a fresh
        # attempt number, so assignment dirs never collide and "highest
        # attempt wins" stays well-defined on the worker side
        self._attempts: Dict[str, int] = {}
        self.stats: Dict[str, int] = {
            "dispatched": 0,
            "delivered": 0,
            "redispatches": 0,
            "degradations": 0,
            "recoveries": 0,
            "hub_restarts": 0,
            "transport_errors": 0,
        }

    # -- weight broadcast -------------------------------------------------

    def ensure_published(
        self,
        version: int,
        arrays_fn: Callable[[], Dict[str, np.ndarray]],
        post_publish: Optional[Callable[[str], None]] = None,
    ) -> None:
        """Publish the policy snapshot for ``version`` if due
        (``fleet.broadcast_every`` versions since the last publish).
        ``post_publish(path)`` is the chaos seam (``broadcast_corrupt``
        bit-flips the landed snapshot). A transport outage mid-publish
        leaves the cursor UNMOVED so the next call republishes; workers
        keep their held version through the gap (staleness-gated)."""
        if self._published_version is not None and (
            version - self._published_version < self.cfg.broadcast_every
        ):
            return
        try:
            path = self.broadcast.publish(version, arrays_fn())
        except (OSError, ConnectionError) as e:
            self.stats["transport_errors"] += 1
            logger.error(
                "fleet: broadcast publish of version %d failed (%s); "
                "will retry next cycle", version, e,
            )
            return
        self._published_version = version
        if post_publish is not None:
            post_publish(path)

    def reset_published(self) -> None:
        """Forget the publish cursor. An in-process restore (guardrail
        rollback, explicit load) can move the policy version BACKWARDS;
        a cursor left ahead of it would make ensure_published skip
        forever and workers would keep generating with the discarded
        weights — admitted as non-stale, since their version reads as
        newer. The next ensure_published republishes unconditionally
        (publish() replaces a leftover same-version tree wholesale:
        the restored params ARE that version)."""
        self._published_version = None

    @property
    def broadcast_version(self) -> Optional[int]:
        return self._published_version

    # -- membership-facing helpers ---------------------------------------

    def live_workers(self) -> List[str]:
        return self.registry.live_workers()

    def select_worker(self, exclude: Tuple[str, ...] = ()) -> Optional[str]:
        """Round-robin over the live, non-excluded set (excluded = the
        worker(s) already tried for this chunk)."""
        live = [w for w in self.live_workers() if w not in exclude]
        if not live:
            return None
        self._rr += 1
        return live[self._rr % len(live)]

    def note_degraded(self, detail: str) -> bool:
        """Record a healthy->degraded transition. Returns True exactly
        once per transition (the caller trips the ``fleet`` guardrail
        signal on True, so a long outage is one trip, not thousands)."""
        if self.degraded:
            return False
        self.degraded = True
        self.stats["degradations"] += 1
        logger.error("fleet DEGRADED: %s — falling back to in-process "
                     "rollout production", detail)
        return True

    def note_recovered(self) -> None:
        if self.degraded:
            self.degraded = False
            self.stats["recoveries"] += 1
            logger.warning(
                "fleet recovered: %d live workers — resuming fleet "
                "production", len(self.live_workers()),
            )

    # -- chunk dispatch / delivery ---------------------------------------

    def next_attempt(self, chunk_id: Tuple[int, int]) -> int:
        name = chunk_name(chunk_id)
        self._attempts[name] = self._attempts.get(name, 0) + 1
        return self._attempts[name]

    def dispatch(
        self,
        chunk_id: Tuple[int, int],
        attempt: int,
        worker: str,
        meta: Dict[str, Any],
        arrays: Dict[str, np.ndarray],
    ) -> bool:
        """Post the assignment. False on a transport outage — the
        caller treats it like an empty live set (degrade to in-process
        production, bit-identical by the replay contract) and the
        attempt number is simply never answered."""
        name = f"{chunk_name(chunk_id)}_a{int(attempt)}"
        try:
            self.transport.put(
                DISPATCH_DIR, name,
                {**meta, "worker": worker, "attempt": int(attempt),
                 "chunk_id": list(chunk_id)},
                arrays,
                meta_name="assignment.json",
            )
        except (OSError, ConnectionError) as e:
            self.stats["transport_errors"] += 1
            logger.error(
                "fleet: dispatch of chunk %s attempt %d failed (%s)",
                chunk_id, attempt, e,
            )
            return False
        self.stats["dispatched"] += 1
        if attempt > 1:
            self.stats["redispatches"] += 1
        logger.info(
            "fleet: dispatched chunk %s attempt %d to worker %r",
            chunk_id, attempt, worker,
        )
        return True

    def poll_delivery(
        self, chunk_id: Tuple[int, int]
    ) -> Optional[Tuple[Dict[str, Any], Dict[str, np.ndarray]]]:
        try:
            msg = self.transport.get(
                CHUNKS_DIR, chunk_name(chunk_id), meta_name="chunk.json"
            )
        except (OSError, ConnectionError):
            # mid-outage reads as not-yet-delivered; the poll loop's
            # eviction scan / dispatch timeout owns escalation
            self.stats["transport_errors"] += 1
            return None
        if msg is not None:
            self.stats["delivered"] += 1
        return msg

    def clear_delivery(self, chunk_id: Tuple[int, int]) -> None:
        """Drop ONLY the delivered payload (a lingering worker's late
        delivery from an abandoned attempt) — the outstanding dispatch
        assignment stays, so the currently-assigned worker is not
        stranded."""
        try:
            self.transport.delete(CHUNKS_DIR, chunk_name(chunk_id))
        except (OSError, ConnectionError):
            self.stats["transport_errors"] += 1

    def clear_chunk(self, chunk_id: Tuple[int, int]) -> None:
        """Drop a consumed chunk's delivery + dispatch messages (the
        transport queue owns the payload now; leftovers would only
        confuse a postmortem — and on a volatile hub a restart clears
        them anyway, so failure here is ignorable)."""
        name = chunk_name(chunk_id)
        try:
            self.transport.delete(CHUNKS_DIR, name)
            self.transport.delete_prefix(DISPATCH_DIR, f"{name}_a")
        except (OSError, ConnectionError):
            self.stats["transport_errors"] += 1

    # -- hub lifecycle (chaos + recovery) --------------------------------

    def crash_hub(self) -> bool:
        """Chaos ``hub_crash`` body: crash-and-relaunch the learner-
        hosted hub with ALL volatile state lost — the worst observable
        outcome of a supervised hub restart. No-op (False) when the
        fleet isn't hosting one (shared-fs, or external host_hub=false
        hub whose lifecycle the supervisor owns)."""
        if self.hub is None:
            return False
        self.hub.restart()
        self.stats["hub_restarts"] += 1
        # volatile records are gone: re-stamp the attach epoch so
        # workers' next membership poll sees the SAME epoch (no forced
        # re-register storm) and the clean-finish semantics survive
        try:
            self.registry.control.put_record(
                "", MEMBERSHIP_RECORD,
                {"epoch": self.membership_epoch, "learner": "learner",
                 "stamped_at": self._clock()},
            )
        except (OSError, ConnectionError):
            self.stats["transport_errors"] += 1
        return True

    # -- persistence / teardown ------------------------------------------

    def state(self) -> Dict[str, Any]:
        """What the checkpoint persists (state.json ``fleet`` section):
        the membership epoch a resumed learner must bump past, the
        last broadcast version (verify_ckpt.py's torn-commit check
        compares it against the exp cursor's policy version) and the
        publish cadence that bounds their legal gap."""
        return {
            "membership_epoch": int(self.membership_epoch),
            "broadcast_version": (
                -1 if self._published_version is None
                else int(self._published_version)
            ),
            "broadcast_every": int(self.cfg.broadcast_every),
        }

    def shutdown(
        self, reason: str = "clean finish",
        grace_s: Optional[float] = None,
    ) -> None:
        """Write the clean-finish flag, then tear down. When this
        learner hosts the hub the flag lives in HUB memory — closing
        immediately would take it away before workers poll it — so we
        wait (bounded by ``grace_s``, default ``2 * worker_ttl_s``)
        until every current-epoch worker's heartbeat goes silent,
        i.e. every worker has seen the flag and exited its beat
        loop."""
        self.registry.shutdown(reason)
        if self.hub is None:
            return
        grace = (
            float(grace_s) if grace_s is not None
            else max(2.0 * self.cfg.worker_ttl_s, 1.0)
        )
        beat_gap = 3.0 * max(
            min(self.cfg.worker_ttl_s / 4.0, 1.0), 0.02
        )
        deadline = time.time() + grace
        while time.time() < deadline:
            recs = self.registry.worker_records()
            now = time.time()  # wall clock — matches worker beats
            if all(
                now - rec.get("last_beat", 0.0) > beat_gap
                for rec in recs.values()
                if rec.get("epoch") == self.membership_epoch
            ):
                break
            time.sleep(max(self.cfg.poll_s, 0.02))
        self.hub.close()

    def stats_summary(self) -> Dict[str, Any]:
        return {
            **self.stats,
            **{f"membership_{k}": v for k, v in self.registry.stats.items()},
            **{f"broadcast_{k}": v for k, v in self.broadcast.stats.items()},
            "live_workers": len(self.live_workers()),
            "membership_epoch": self.membership_epoch,
            "degraded": int(self.degraded),
        }
