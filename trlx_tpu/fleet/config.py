"""Parsed ``ppo.fleet`` section (plain dict in YAML).

The fleet rides ON TOP of the experience transport (``ppo.exp.*``):
``fleet.enabled`` routes chunk PRODUCTION to registered cross-process
rollout workers, while delivery/dedup/staleness/cursor semantics stay
the transport's. Everything here is host-side and jax-free.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, Optional


@dataclass(frozen=True)
class FleetConfig:
    """``ppo.fleet.*`` knobs (default off; requires ``ppo.exp.enabled``).

    enabled            master switch: route chunk production to the
                       cross-process rollout-worker fleet. Fault-free
                       the fleet path is golden-checked bit-equal to
                       the in-process ``ppo.exp.enabled`` path.
    dir                fleet coordination directory (worker registry,
                       weight broadcast, chunk dispatch/delivery) —
                       must be shared between learner and workers.
                       Empty = ``<train.checkpoint_dir>/fleet``.
    min_workers        live workers below which the learner DEGRADES:
                       the ``fleet`` guardrail signal trips and chunk
                       production falls back to the in-process path
                       (bit-equal to the fleet-less run) until workers
                       return.
    worker_ttl_s       seconds a worker's membership heartbeat may go
                       silent before it is evicted, its in-flight
                       chunk re-dispatched (replay snapshot intact),
                       and a flap recorded.
    startup_timeout_s  how long the learner's FIRST production waits
                       for ``min_workers`` to register before
                       degrading (a fleet that never comes up must not
                       wedge the run).
    dispatch_timeout_s hard bound on waiting for a single dispatched
                       chunk before the learner degrades and produces
                       it in-process (backstop behind eviction; the
                       regeneration is bit-identical by the replay
                       snapshot).
    poll_s             poll (and watchdog-beat) cadence of the
                       learner's bounded waits and the worker loop.
    flap_limit         evictions/rejoins in a row before a worker is
                       QUARANTINED (excluded from dispatch).
    flap_backoff_s     first quarantine duration; doubles per repeat
                       quarantine of the same worker.
    broadcast_every    publish a weight snapshot every N policy
                       versions (1 = every optimizer cycle). Workers
                       between publishes generate with the previous
                       version; the chunks flow through the
                       ``exp.staleness`` gate like any stale delivery.
    broadcast_keep     published snapshot versions retained on disk
                       (the previous version is what a worker keeps
                       when a fresh snapshot fails manifest
                       verification).
    attach_timeout_s   how long a WORKER waits for the learner's
                       membership record to appear before giving up.
    detach_timeout_s   how long the membership record may stay
                       unreadable/absent AFTER a successful attach
                       before the worker concludes the learner AND its
                       hub are gone for good and exits CLEAN (its
                       durable output is the chunks it delivered). A
                       learner restart or hub relaunch inside the
                       window just re-registers the worker — this
                       fires only when nothing ever comes back, e.g. a
                       hosted hub that closed while this worker's link
                       was partitioned.
    transport          the fleet's ENTIRE cross-process substrate
                       (exp/net.py spec) — chunk dispatch/delivery,
                       membership records, the shutdown flag, AND the
                       weight broadcast all ride it: ``{}`` =
                       ``{backend: shared_fs}`` rooted at ``dir`` (the
                       golden pre-interface layout, bit-equal).
                       ``{backend: tcp, port: N, host: <learner addr>,
                       bind: 0.0.0.0}`` makes the LEARNER host a
                       socket hub (use a fixed non-zero port so
                       workers can find it; workers connect to
                       ``host:port`` with the same spec dict) and the
                       broadcast goes chunked-with-sha256-resume over
                       the socket — workers then need NO shared
                       filesystem at all. Add ``host_hub: false`` to
                       point every role at an EXTERNAL supervised hub
                       (``python -m trlx_tpu.exp.net``), ``retries``/
                       ``timeout_s``/``rpc_deadline_s`` to tune the
                       client's retry ladder, and a ``faults``
                       sub-dict for the deterministic per-link fault
                       injector (docs/serving.md "Transport backends",
                       docs/robustness.md "Network fault model").
    """

    enabled: bool = False
    dir: str = ""
    min_workers: int = 1
    worker_ttl_s: float = 30.0
    startup_timeout_s: float = 20.0
    dispatch_timeout_s: float = 600.0
    poll_s: float = 0.05
    flap_limit: int = 3
    flap_backoff_s: float = 5.0
    broadcast_every: int = 1
    broadcast_keep: int = 2
    attach_timeout_s: float = 120.0
    detach_timeout_s: float = 60.0
    transport: Optional[Dict[str, Any]] = None

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "FleetConfig":
        d = dict(d or {})
        known = set(cls.__dataclass_fields__)
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"ppo.fleet: unknown keys {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        cfg = cls(**d)
        if cfg.min_workers < 1:
            raise ValueError("fleet.min_workers must be >= 1")
        if cfg.worker_ttl_s <= 0:
            raise ValueError("fleet.worker_ttl_s must be > 0")
        if cfg.flap_limit < 1:
            raise ValueError("fleet.flap_limit must be >= 1")
        if cfg.broadcast_every < 1:
            raise ValueError("fleet.broadcast_every must be >= 1")
        if cfg.detach_timeout_s <= 0:
            raise ValueError("fleet.detach_timeout_s must be > 0")
        return cfg

    def resolved_dir(self, checkpoint_dir: str) -> str:
        return self.dir or os.path.join(checkpoint_dir, "fleet")
