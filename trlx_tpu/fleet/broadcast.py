"""Versioned weight broadcast: learner publishes, workers refresh.

One snapshot per policy version under ``<fleet>/broadcast/``::

    broadcast/
      vNNNNNNNN/arrays.npz     path-keyed host copies of the params
      vNNNNNNNN/meta.json      {"version": N, ...}
      vNNNNNNNN/integrity.json per-file sha256 (the PR 4 machinery)
      CURRENT.json             {"version": N, "path": "vNNNNNNNN"}

Publication uses the checkpoint commit discipline: write into a temp
directory, manifest + fsync, one atomic rename, THEN flip the CURRENT
pointer — a learner dying mid-publish leaves the previous version
intact and pointed-to. Consumption verifies the manifest BEFORE
loading: a corrupt or torn snapshot (bit-rot, a half-replicated
shared-filesystem read) is rejected and the worker KEEPS its previous
version — broadcast failure degrades to off-policy data the
``exp.staleness`` gate corrects, never to wrong weights.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, Optional, Tuple

import numpy as np

from trlx_tpu.utils import logging
from trlx_tpu.utils.checkpointing import (
    atomic_json_write,
    fsync_tree,
    verify_integrity,
    write_integrity_manifest,
)

logger = logging.get_logger(__name__)

CURRENT_FILE = "CURRENT.json"
ARRAYS_FILE = "arrays.npz"
META_FILE = "meta.json"


class BroadcastCorrupt(RuntimeError):
    """A published snapshot failed manifest verification on fetch."""


def _version_name(version: int) -> str:
    return f"v{int(version):08d}"


class WeightBroadcast:
    """Filesystem weight-snapshot channel (learner publishes, any
    number of workers fetch). Host-side and jax-free: params arrive as
    a path-keyed dict of numpy arrays (``fleet/serde.py`` converts)."""

    def __init__(self, root: str, keep: int = 2):
        self.root = root
        self.keep = max(int(keep), 1)
        os.makedirs(root, exist_ok=True)
        self.stats: Dict[str, int] = {
            "published": 0,
            "fetched": 0,
            "corrupt_rejected": 0,
        }

    # -- learner side -----------------------------------------------------

    def publish(
        self,
        version: int,
        arrays: Dict[str, np.ndarray],
        meta: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Atomically publish ``arrays`` as snapshot ``version`` and
        flip CURRENT to it. Returns the snapshot directory.
        Re-publishing an existing version (learner relaunch resuming at
        the same policy version) replaces it wholesale — the restored
        params ARE that version; a leftover tree from the previous
        incarnation may be torn."""
        name = _version_name(version)
        final = os.path.join(self.root, name)
        tmp = os.path.join(self.root, f".tmp_{name}_{os.getpid()}")
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        with open(os.path.join(tmp, ARRAYS_FILE), "wb") as f:
            np.savez(f, **arrays)
        atomic_json_write(
            os.path.join(tmp, META_FILE),
            {"version": int(version), **(meta or {})},
        )
        write_integrity_manifest(tmp)
        fsync_tree(tmp)
        if os.path.isdir(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
        atomic_json_write(
            os.path.join(self.root, CURRENT_FILE),
            {"version": int(version), "path": name},
        )
        self.stats["published"] += 1
        self._apply_retention()
        logger.info(
            "weight broadcast: published policy version %d (%s)",
            version, final,
        )
        return final

    def _apply_retention(self) -> None:
        versions = sorted(
            e for e in os.listdir(self.root)
            if e.startswith("v") and e[1:].isdigit()
        )
        for stale in versions[: -self.keep]:
            shutil.rmtree(
                os.path.join(self.root, stale), ignore_errors=True
            )

    # -- worker side ------------------------------------------------------

    def current_version(self) -> Optional[int]:
        import json

        try:
            with open(os.path.join(self.root, CURRENT_FILE)) as f:
                return int(json.load(f)["version"])
        except (OSError, ValueError, KeyError):
            return None

    def fetch(self) -> Tuple[int, Dict[str, np.ndarray]]:
        """Load the CURRENT snapshot, manifest-verified first. Raises
        :class:`BroadcastCorrupt` on mismatch (the caller keeps its
        previous version and retries later) and ``FileNotFoundError``
        when nothing is published yet."""
        import json

        with open(os.path.join(self.root, CURRENT_FILE)) as f:
            cur = json.load(f)
        directory = os.path.join(self.root, cur["path"])
        status, problems = verify_integrity(directory)
        if status != "ok":
            self.stats["corrupt_rejected"] += 1
            raise BroadcastCorrupt(
                f"broadcast snapshot {directory} failed verification "
                f"({status}): {problems[:3]}"
            )
        with np.load(os.path.join(directory, ARRAYS_FILE)) as z:
            arrays = {k: z[k] for k in z.files}
        self.stats["fetched"] += 1
        return int(cur["version"]), arrays
