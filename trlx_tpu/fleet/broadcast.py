"""Versioned weight broadcast: learner publishes, workers refresh.

Two implementations behind one publish/fetch surface:

:class:`WeightBroadcast` — the golden shared-filesystem channel. One
snapshot per policy version under ``<fleet>/broadcast/``::

    broadcast/
      vNNNNNNNN/arrays.npz     path-keyed host copies of the params
      vNNNNNNNN/meta.json      {"version": N, ...}
      vNNNNNNNN/integrity.json per-file sha256 (the PR 4 machinery)
      CURRENT.json             {"version": N, "path": "vNNNNNNNN"}

Publication uses the checkpoint commit discipline: write into a temp
directory, manifest + fsync, one atomic rename, THEN flip the CURRENT
pointer — a learner dying mid-publish leaves the previous version
intact and pointed-to.

:class:`ChunkedBroadcast` — the same contract over any ``exp/net.py``
Transport (i.e. no shared filesystem): the snapshot is split into
size-bounded array chunks published as immutable messages, described
by a manifest RECORD carrying a per-chunk sha256, with a CURRENT
record flipped last. Workers verify each chunk's digest as it arrives
and keep verified chunks in a local resume cache, so a partition or
torn transfer mid-fetch costs a retry of the MISSING chunks, not a
full re-download. :func:`make_broadcast` picks the implementation from
the transport backend.

Both consumption paths verify BEFORE loading: a corrupt or torn
snapshot (bit-rot, a half-replicated shared-filesystem read, a
mid-republish chunk swap, a forged frame) is rejected with
:class:`BroadcastCorrupt` and the worker KEEPS its previous version —
broadcast failure degrades to off-policy data the ``exp.staleness``
gate corrects, never to wrong weights.
"""

from __future__ import annotations

import hashlib
import os
import shutil
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from trlx_tpu.utils import logging
from trlx_tpu.utils.checkpointing import (
    atomic_json_write,
    fsync_tree,
    verify_integrity,
    write_integrity_manifest,
)

logger = logging.get_logger(__name__)

CURRENT_FILE = "CURRENT.json"
ARRAYS_FILE = "arrays.npz"
META_FILE = "meta.json"

BROADCAST_TOPIC = "broadcast"
CURRENT_RECORD = "CURRENT"
DEFAULT_CHUNK_BYTES = 4 * 1024 * 1024


class BroadcastCorrupt(RuntimeError):
    """A published snapshot failed manifest verification on fetch."""


def _version_name(version: int) -> str:
    return f"v{int(version):08d}"


class WeightBroadcast:
    """Filesystem weight-snapshot channel (learner publishes, any
    number of workers fetch). Host-side and jax-free: params arrive as
    a path-keyed dict of numpy arrays (``fleet/serde.py`` converts)."""

    def __init__(self, root: str, keep: int = 2):
        self.root = root
        self.keep = max(int(keep), 1)
        os.makedirs(root, exist_ok=True)
        self.stats: Dict[str, int] = {
            "published": 0,
            "fetched": 0,
            "corrupt_rejected": 0,
        }

    # -- learner side -----------------------------------------------------

    def publish(
        self,
        version: int,
        arrays: Dict[str, np.ndarray],
        meta: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Atomically publish ``arrays`` as snapshot ``version`` and
        flip CURRENT to it. Returns the snapshot directory.
        Re-publishing an existing version (learner relaunch resuming at
        the same policy version) replaces it wholesale — the restored
        params ARE that version; a leftover tree from the previous
        incarnation may be torn."""
        name = _version_name(version)
        final = os.path.join(self.root, name)
        tmp = os.path.join(self.root, f".tmp_{name}_{os.getpid()}")
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        with open(os.path.join(tmp, ARRAYS_FILE), "wb") as f:
            np.savez(f, **arrays)
        atomic_json_write(
            os.path.join(tmp, META_FILE),
            {"version": int(version), **(meta or {})},
        )
        write_integrity_manifest(tmp)
        fsync_tree(tmp)
        if os.path.isdir(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
        atomic_json_write(
            os.path.join(self.root, CURRENT_FILE),
            {"version": int(version), "path": name},
        )
        self.stats["published"] += 1
        self._apply_retention()
        logger.info(
            "weight broadcast: published policy version %d (%s)",
            version, final,
        )
        return final

    def _apply_retention(self) -> None:
        versions = sorted(
            e for e in os.listdir(self.root)
            if e.startswith("v") and e[1:].isdigit()
        )
        for stale in versions[: -self.keep]:
            shutil.rmtree(
                os.path.join(self.root, stale), ignore_errors=True
            )

    # -- worker side ------------------------------------------------------

    def current_version(self) -> Optional[int]:
        import json

        try:
            with open(os.path.join(self.root, CURRENT_FILE)) as f:
                return int(json.load(f)["version"])
        except (OSError, ValueError, KeyError):
            return None

    def fetch(self) -> Tuple[int, Dict[str, np.ndarray]]:
        """Load the CURRENT snapshot, manifest-verified first. Raises
        :class:`BroadcastCorrupt` on mismatch (the caller keeps its
        previous version and retries later) and ``FileNotFoundError``
        when nothing is published yet."""
        import json

        with open(os.path.join(self.root, CURRENT_FILE)) as f:
            cur = json.load(f)
        directory = os.path.join(self.root, cur["path"])
        status, problems = verify_integrity(directory)
        if status != "ok":
            self.stats["corrupt_rejected"] += 1
            raise BroadcastCorrupt(
                f"broadcast snapshot {directory} failed verification "
                f"({status}): {problems[:3]}"
            )
        with np.load(os.path.join(directory, ARRAYS_FILE)) as z:
            arrays = {k: z[k] for k in z.files}
        self.stats["fetched"] += 1
        return int(cur["version"]), arrays


# -- transport-native (chunked, resumable) ------------------------------


def _chunk_digest(arrays: Dict[str, np.ndarray]) -> str:
    """sha256 over the CANONICAL content of a chunk: per-array name,
    dtype, shape, raw bytes, in name order. Deliberately NOT a digest
    of the packed npz blob — zip containers embed timestamps and the
    shared-fs backend re-serializes arrays, so the blob is not stable;
    the array contents are."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        a = np.ascontiguousarray(np.asarray(arrays[name]))
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(tuple(a.shape)).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _plan_chunks(
    arrays: Dict[str, np.ndarray], chunk_bytes: int
) -> List[List[str]]:
    """Greedy size-bounded grouping of array names (name order, so the
    plan — and therefore every chunk digest — is deterministic for a
    given params tree). An array larger than the budget gets a chunk
    of its own rather than failing."""
    groups: List[List[str]] = []
    current: List[str] = []
    used = 0
    for name in sorted(arrays):
        size = int(np.asarray(arrays[name]).nbytes)
        if current and used + size > chunk_bytes:
            groups.append(current)
            current, used = [], 0
        current.append(name)
        used += size
    if current:
        groups.append(current)
    return groups


class ChunkedBroadcast:
    """Weight-snapshot channel over a Transport (tcp hub, or anything
    else) — the no-shared-filesystem counterpart of
    :class:`WeightBroadcast` with the same publish/fetch surface.

    Wire layout in topic ``broadcast``:

      message ``vNNNNNNNN_cIIII``  one chunk: its arrays + meta
                                   {version, chunk, sha256}
      record  ``vNNNNNNNN``        the manifest: ordered chunk list
                                   with per-chunk sha256 + array names
      record  ``CURRENT``          {"version": N, "path": "vNNNNNNNN"}
                                   — flipped LAST, so a learner dying
                                   mid-publish leaves the previous
                                   version pointed-to (same commit
                                   discipline as the fs channel)

    Fetch verifies every chunk digest against the manifest and stores
    verified chunks in an in-memory resume cache keyed (name, sha):
    when a partition tears a fetch, the caller's retry re-reads ONLY
    the chunks it doesn't hold — per-chunk resume, not a re-download.
    A manifest/chunk that stays missing or corrupt raises
    :class:`BroadcastCorrupt`; an unreachable transport raises
    ``ConnectionError`` (an ``OSError``) — both land in the worker's
    keep-prior-version path.

    ``chaos`` arms the ``broadcast_torn_fetch`` site: consulted once
    per chunk actually read off the transport (resume-cache hits skip
    it — they cost no network), a fire tears that chunk's transfer.
    """

    def __init__(
        self,
        transport,
        keep: int = 2,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        chaos=None,
    ):
        self.transport = transport
        self.keep = max(int(keep), 1)
        self.chunk_bytes = max(int(chunk_bytes), 1)
        self.chaos = chaos
        self._cache: Dict[Tuple[str, str], Dict[str, np.ndarray]] = {}
        self.stats: Dict[str, int] = {
            "published": 0,
            "fetched": 0,
            "corrupt_rejected": 0,
            "chunks_fetched": 0,
            "chunks_resumed": 0,
            "torn_fetches": 0,
        }

    # -- learner side -----------------------------------------------------

    def publish(
        self,
        version: int,
        arrays: Dict[str, np.ndarray],
        meta: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Publish ``arrays`` as snapshot ``version`` and flip CURRENT
        to it. Returns the version name (the fs channel returns a
        directory; callers treat it as an opaque label). Re-publishing
        an existing version (learner relaunch, hub restart losing the
        messages) replaces it wholesale."""
        name = _version_name(version)
        # wipe any torn previous incarnation of this version first —
        # chunk messages are immutable (dedup), so a changed chunk
        # would otherwise silently keep its old payload
        self.transport.delete_prefix(BROADCAST_TOPIC, f"{name}_c")
        chunks = []
        for i, group in enumerate(_plan_chunks(arrays, self.chunk_bytes)):
            chunk_arrays = {k: np.asarray(arrays[k]) for k in group}
            digest = _chunk_digest(chunk_arrays)
            cname = f"{name}_c{i:04d}"
            self.transport.put(
                BROADCAST_TOPIC, cname,
                {"version": int(version), "chunk": i, "sha256": digest},
                chunk_arrays,
            )
            chunks.append({"name": cname, "sha256": digest,
                           "arrays": sorted(group)})
        self.transport.put_record(
            BROADCAST_TOPIC, name,
            {"version": int(version), "chunks": chunks,
             **(meta or {})},
        )
        self.transport.put_record(
            BROADCAST_TOPIC, CURRENT_RECORD,
            {"version": int(version), "path": name},
        )
        self.stats["published"] += 1
        self._apply_retention(version)
        logger.info(
            "weight broadcast: published policy version %d (%d chunks "
            "over transport)", version, len(chunks),
        )
        return name

    def _apply_retention(self, version: int) -> None:
        try:
            names = self.transport.list_records(BROADCAST_TOPIC)
        except (OSError, ConnectionError):
            return
        versions = sorted(
            n for n in names if n.startswith("v") and n[1:].isdigit()
        )
        for stale in versions[: -self.keep]:
            try:
                self.transport.delete_prefix(BROADCAST_TOPIC, f"{stale}_c")
                self.transport.delete_record(BROADCAST_TOPIC, stale)
            except (OSError, ConnectionError):
                return

    # -- worker side ------------------------------------------------------

    def current_version(self) -> Optional[int]:
        try:
            cur = self.transport.get_record(BROADCAST_TOPIC, CURRENT_RECORD)
            return int(cur["version"]) if cur else None
        except (OSError, ConnectionError, ValueError, KeyError):
            return None

    def fetch(self) -> Tuple[int, Dict[str, np.ndarray]]:
        """Assemble the CURRENT snapshot chunk by chunk, digest-
        verified. Raises :class:`BroadcastCorrupt` on a missing/
        mismatched chunk or manifest, ``FileNotFoundError`` when
        nothing is published yet, ``ConnectionError`` mid-partition;
        verified chunks survive in the resume cache either way."""
        cur = self.transport.get_record(BROADCAST_TOPIC, CURRENT_RECORD)
        if cur is None:
            raise FileNotFoundError("broadcast: nothing published yet")
        name = str(cur["path"])
        manifest = self.transport.get_record(BROADCAST_TOPIC, name)
        if manifest is None:
            # CURRENT flipped but the manifest is gone: a hub restart
            # ate the records mid-read, or retention raced us
            self.stats["corrupt_rejected"] += 1
            raise BroadcastCorrupt(
                f"broadcast: manifest {name} missing behind CURRENT"
            )
        # the cache only ever serves the version being fetched
        self._cache = {
            k: v for k, v in self._cache.items()
            if k[0].startswith(f"{name}_c")
        }
        out: Dict[str, np.ndarray] = {}
        for entry in manifest.get("chunks", []):
            cname, sha = str(entry["name"]), str(entry["sha256"])
            cached = self._cache.get((cname, sha))
            if cached is not None:
                self.stats["chunks_resumed"] += 1
                out.update(cached)
                continue
            if self.chaos is not None and self.chaos.consult(
                "broadcast_torn_fetch"
            ):
                self.stats["torn_fetches"] += 1
                raise BroadcastCorrupt(
                    f"broadcast: chunk {cname} transfer torn (chaos)"
                )
            msg = self.transport.get(BROADCAST_TOPIC, cname)
            if msg is None:
                self.stats["corrupt_rejected"] += 1
                raise BroadcastCorrupt(
                    f"broadcast: chunk {cname} missing (torn publish or "
                    f"hub restart)"
                )
            _, arrays = msg
            if _chunk_digest(arrays) != sha:
                self.stats["corrupt_rejected"] += 1
                raise BroadcastCorrupt(
                    f"broadcast: chunk {cname} failed sha256 verification"
                )
            self._cache[(cname, sha)] = arrays
            self.stats["chunks_fetched"] += 1
            out.update(arrays)
        self.stats["fetched"] += 1
        self._cache.clear()  # assembled — the resume window is over
        return int(cur["version"]), out


def make_broadcast(
    transport,
    keep: int = 2,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    chaos=None,
):
    """Pick the broadcast channel for a transport: the golden
    filesystem snapshot layout when the BACKEND is shared-fs (learner
    and worker may disagree on fault wrappers, so the choice keys on
    the unwrapped backend — both sides must speak the same layout),
    chunked-over-transport otherwise. On shared-fs the snapshot files
    are read directly (not through any fault wrapper): the injector
    models network links, and the golden path has none."""
    from trlx_tpu.exp.net import SharedFSTransport, base_transport

    base = base_transport(transport)
    if isinstance(base, SharedFSTransport):
        return WeightBroadcast(
            os.path.join(base.root, BROADCAST_TOPIC), keep=keep
        )
    return ChunkedBroadcast(
        transport, keep=keep, chunk_bytes=chunk_bytes, chaos=chaos
    )
