"""Wire conversions for the fleet: pytrees <-> path-keyed numpy.

Everything that crosses the learner/worker process boundary goes
through here, and every conversion is EXACT (float32 arrays round-trip
through ``.npz`` bit-for-bit) — that is what makes a fleet-produced
chunk bit-identical to the in-process one. The jax imports live here
so ``membership``/``broadcast``/``config`` stay host-only.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import numpy as np


def _jax():
    import jax

    return jax


# -- PRNG keys (mirrors base.py _pack_rng/_unpack_rng) -----------------


def pack_rng(rng) -> list:
    jax = _jax()
    try:
        data = jax.random.key_data(rng)
    except Exception:  # old-style raw uint32 key array
        data = rng
    return np.asarray(data).astype(np.uint32).tolist()


def unpack_rng(data, like):
    """Rebuild a key with the same flavor (typed/raw) as ``like``."""
    import jax.numpy as jnp

    jax = _jax()
    arr = jnp.asarray(np.asarray(data, np.uint32))
    try:
        if jnp.issubdtype(like.dtype, jax.dtypes.prng_key):
            arr = jax.random.wrap_key_data(arr)
    except Exception:
        pass
    return arr


# -- producer replay snapshot (rng + reward accounting) ----------------


def snapshot_to_wire(snap: Dict[str, Any]) -> Dict[str, Any]:
    """The PPO ``_exp_snapshot`` dict (rng, running moments, ref
    stats) as JSON-safe values. float32 scalars widen to python floats
    exactly (float64 is a superset), so the round-trip is bit-free."""
    rm = snap["running_moments"]
    return {
        "rng": pack_rng(snap["rng"]),
        "running_moments": {
            "mean": float(np.asarray(rm.mean)),
            "var": float(np.asarray(rm.var)),
            "std": float(np.asarray(rm.std)),
            "count": float(np.asarray(rm.count)),
        },
        "ref_mean": (
            None if snap.get("ref_mean") is None else float(snap["ref_mean"])
        ),
        "ref_std": (
            None if snap.get("ref_std") is None else float(snap["ref_std"])
        ),
    }


def snapshot_from_wire(wire: Dict[str, Any], like_rng) -> Dict[str, Any]:
    import jax.numpy as jnp

    from trlx_tpu.ops.common import RunningMoments

    rm = wire["running_moments"]
    return {
        "rng": unpack_rng(wire["rng"], like_rng),
        "running_moments": RunningMoments(
            mean=jnp.float32(rm["mean"]), var=jnp.float32(rm["var"]),
            std=jnp.float32(rm["std"]), count=jnp.float32(rm["count"]),
        ),
        "ref_mean": wire["ref_mean"],
        "ref_std": wire["ref_std"],
    }


# -- prompt batches and rollout batches --------------------------------


def prompt_batch_to_arrays(batch) -> Tuple[Dict[str, np.ndarray], Any]:
    """PromptBatch device arrays -> numpy (+ the host-side metadata,
    which rides the JSON half of the assignment)."""
    return (
        {
            "prompt_input_ids": np.asarray(batch.input_ids),
            "prompt_attention_mask": np.asarray(batch.attention_mask),
        },
        batch.metadata,
    )


def prompt_batch_from_arrays(arrays: Dict[str, np.ndarray], metadata):
    import jax.numpy as jnp

    from trlx_tpu.data import PromptBatch

    return PromptBatch(
        input_ids=jnp.asarray(arrays["prompt_input_ids"]),
        attention_mask=jnp.asarray(arrays["prompt_attention_mask"]),
        metadata=metadata,
    )


_ROLLOUT_FIELDS = (
    "query_tensors",
    "response_tensors",
    "logprobs",
    "values",
    "rewards",
    "response_mask",
    "is_weight",  # None outside staleness clip mode
)


def rollout_to_arrays(rb) -> Dict[str, np.ndarray]:
    out = {}
    for name in _ROLLOUT_FIELDS:
        leaf = getattr(rb, name)
        if leaf is not None:
            out[f"rollout_{name}"] = np.asarray(leaf)
    return out


def rollout_from_arrays(arrays: Dict[str, np.ndarray]):
    import jax.numpy as jnp

    from trlx_tpu.data import PPORolloutBatch

    kw = {}
    for name in _ROLLOUT_FIELDS:
        key = f"rollout_{name}"
        if key in arrays:
            kw[name] = jnp.asarray(arrays[key])
    return PPORolloutBatch(**kw)


# -- params <-> path-keyed numpy (weight broadcast) --------------------


def params_to_arrays(params) -> Dict[str, np.ndarray]:
    """Flatten a param pytree to ``{keystr: host array}``. ``keystr``
    is jax's canonical path string, so learner and worker agree on
    names as long as they built the same model (same config)."""
    jax = _jax()
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    return {
        jax.tree_util.keystr(path): np.asarray(leaf)
        for path, leaf in leaves
    }


def load_params_like(params, arrays: Dict[str, np.ndarray]):
    """Rebuild a device param tree shaped like ``params`` from a
    broadcast snapshot: every leaf keeps its dtype and sharding (the
    snapshot's bytes, the holder's placement)."""
    jax = _jax()

    def restore(path, old):
        key = jax.tree_util.keystr(path)
        if key not in arrays:
            raise KeyError(
                f"broadcast snapshot is missing param leaf {key} — "
                "learner and worker built different models (config "
                "drift between processes)"
            )
        new = np.asarray(arrays[key])
        if new.shape != old.shape:
            raise ValueError(
                f"broadcast leaf {key} has shape {new.shape}, the "
                f"worker's model expects {old.shape}"
            )
        return jax.device_put(new.astype(old.dtype), old.sharding)

    return jax.tree_util.tree_map_with_path(restore, params)


# -- chunk payload stats ------------------------------------------------


def stats_to_wire(stats: Dict[str, Any]) -> Dict[str, float]:
    """Chunk stats (host floats + device scalars) -> plain floats.
    Device scalars materialize here — on the WORKER, so the learner
    never blocks on a fleet chunk's stats."""
    return {k: float(np.asarray(v)) for k, v in stats.items()}


# -- atomic directory commit (dispatch + delivery messages) ------------


def commit_message_dir(
    final_dir: str,
    meta: Dict[str, Any],
    arrays: Dict[str, np.ndarray],
    meta_name: str = "meta.json",
) -> bool:
    """Write a message as ``<dir>/{meta.json,arrays.npz}`` via the
    tmp-dir + rename pattern: the destination appears atomically and
    complete, or not at all. Returns False when the destination
    already exists (a racing duplicate — e.g. a partitioned worker
    delivering a chunk its replacement already delivered); the caller
    treats that as success-by-dedup."""
    import json as _json
    import shutil as _shutil

    from trlx_tpu.utils.checkpointing import fsync_tree

    if os.path.isdir(final_dir):
        return False
    parent = os.path.dirname(final_dir)
    os.makedirs(parent, exist_ok=True)
    tmp = f"{final_dir}.tmp_{os.getpid()}"
    _shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    with open(os.path.join(tmp, "arrays.npz"), "wb") as f:
        np.savez(f, **arrays)
    with open(os.path.join(tmp, meta_name), "w") as f:
        _json.dump(meta, f)
    fsync_tree(tmp)
    try:
        os.rename(tmp, final_dir)
    except OSError:
        _shutil.rmtree(tmp, ignore_errors=True)
        return False
    return True


def read_message_meta(
    final_dir: str, meta_name: str = "meta.json"
) -> Optional[Dict[str, Any]]:
    """Meta-only read of a committed message dir — for callers that
    route on the metadata (which worker an assignment addresses)
    without paying the arrays load on every poll tick."""
    import json as _json

    meta_fp = os.path.join(final_dir, meta_name)
    if not (
        os.path.isfile(meta_fp)
        and os.path.isfile(os.path.join(final_dir, "arrays.npz"))
    ):
        return None
    with open(meta_fp) as f:
        return _json.load(f)


def read_message_dir(
    final_dir: str, meta_name: str = "meta.json"
) -> Optional[Tuple[Dict[str, Any], Dict[str, np.ndarray]]]:
    """Read a committed message dir; None when absent (rename not
    landed yet)."""
    meta = read_message_meta(final_dir, meta_name)
    if meta is None:
        return None
    with np.load(os.path.join(final_dir, "arrays.npz")) as z:
        arrays = {k: z[k] for k in z.files}
    return meta, arrays
