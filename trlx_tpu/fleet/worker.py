"""The rollout worker: a cross-process producer behind the transport.

A worker is a full (but learner-less) PPO trainer: same config, same
jitted sampler and score path, driven by dispatch messages instead of
a training loop. Per assignment it restores the replay snapshot the
learner attached (RNG + reward running-moments + ref stats), refreshes
its policy weights from the versioned broadcast, generates and scores
the chunk through the SAME ``_score_and_assemble`` the learner uses,
and delivers the payload plus its post-production snapshot — which the
learner adopts, so the learner's RNG/moments chain is bit-identical to
having produced the chunk in-process.

Liveness: a daemon thread rewrites the membership record every
fraction of ``fleet.worker_ttl_s`` — process death (or a chaos
partition, which pauses the thread) silences it and the learner
evicts + re-dispatches. A wedged-but-alive worker is the learner's
``fleet.dispatch_timeout_s`` backstop's job.

Entry point::

    from trlx_tpu.fleet.worker import run_worker
    run_worker(config=my_trl_config, reward_fn=my_reward_fn)

``config`` must equal the learner's (model/tokenizer/seed/method) —
the worker rebuilds the frozen reference from it, and a drifted config
shows up as a broadcast param-leaf mismatch, not silent divergence.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from trlx_tpu.fleet import serde
from trlx_tpu.fleet.broadcast import BroadcastCorrupt, make_broadcast
from trlx_tpu.fleet.config import FleetConfig
from trlx_tpu.fleet.coordinator import CHUNKS_DIR, DISPATCH_DIR
from trlx_tpu.fleet.membership import (
    read_membership,
    shutdown_requested,
    write_worker_record,
)
from trlx_tpu.utils import logging
from trlx_tpu.utils.resilient import retry_call

logger = logging.get_logger(__name__)


class FleetWorker:
    def __init__(
        self,
        trainer,
        root: str,
        cfg: FleetConfig,
        worker_id: Optional[str] = None,
        max_chunks: Optional[int] = None,
        transport=None,
    ):
        from trlx_tpu.exp.net import FaultyTransport, make_transport

        self.trainer = trainer
        self.root = root
        self.cfg = cfg
        # ALL cross-process traffic — chunk assignment/delivery AND the
        # control plane (membership records, shutdown flag, weight
        # broadcast) — rides one transport (exp/net.py): must be the
        # SAME backend the learner's coordinator built
        self.transport = transport or make_transport(cfg.transport, root)
        if trainer.chaos is not None and not isinstance(
            self.transport, FaultyTransport
        ):
            # an armed chaos monkey drives this worker's LINK through
            # the net_drop / net_partition sites (the per-link fault
            # injector wraps every transport op this worker makes)
            self.transport = FaultyTransport(
                self.transport, chaos=trainer.chaos
            )
        self.worker_id = worker_id or f"worker-{os.getpid()}"
        self.max_chunks = max_chunks
        self.broadcast = make_broadcast(
            self.transport, keep=cfg.broadcast_keep, chaos=trainer.chaos
        )
        self._held_version: Optional[int] = None
        self._epoch: Optional[int] = None
        self._joined_at: Optional[float] = None
        self._produced = 0
        # ASSIGNMENT entries (chunk + attempt) this process already
        # produced — keyed per attempt, not per chunk, so a staleness
        # regeneration re-dispatched to this same worker is picked up
        # instead of mistaken for the delivered original
        self._done: set = set()
        # liveness beats ride a daemon thread so a long compile inside
        # the first generate cannot read as death; a chaos partition
        # pauses it (beats stop = what the learner can observe)
        self._beat_stop = threading.Event()
        self._beat_pause = threading.Event()

    # -- liveness ---------------------------------------------------------

    def _beat_once(self) -> None:
        if self._epoch is None or self._beat_pause.is_set():
            return
        write_worker_record(
            self.transport, self.worker_id, self._epoch,
            self._held_version, joined_at=self._joined_at,
        )

    def _beat_loop(self) -> None:
        interval = max(min(self.cfg.worker_ttl_s / 4.0, 1.0), 0.02)
        while not self._beat_stop.is_set():
            try:
                self._beat_once()
            except (OSError, ConnectionError):
                # transient shared-fs hiccup / tcp drop / hub restart:
                # the next beat retries — and doubles as the
                # RE-REGISTRATION that recovers from a hub losing its
                # volatile records
                pass
            self._beat_stop.wait(interval)

    # -- membership -------------------------------------------------------

    def _sync_membership(self) -> bool:
        """Poll the membership record; on an epoch bump, re-register
        under the new epoch (the learner-restart handshake). Returns
        False until a learner has attached at all (an unreachable
        control plane reads the same: keep polling)."""
        m = read_membership(self.transport)
        if m is None:
            return False
        epoch = int(m.get("epoch", 0))
        if epoch != self._epoch:
            self._epoch = epoch
            self._joined_at = time.time()
            self._beat_once()  # register immediately, not next tick
            logger.info(
                "fleet worker %r: registered under membership epoch %d",
                self.worker_id, epoch,
            )
        return True

    # -- weights ----------------------------------------------------------

    def _refresh_weights(self) -> None:
        """Adopt the CURRENT broadcast snapshot if it moved, with
        retry/backoff; a snapshot that stays corrupt/torn after the
        retries is SKIPPED and the previous version kept — the chunks
        then carry the older policy version and flow through the
        ``exp.staleness`` gate (off-policy correction, never wrong
        weights)."""
        current = self.broadcast.current_version()
        if current is None or current == self._held_version:
            return
        try:
            version, arrays = retry_call(
                self.broadcast.fetch, retries=2,
                base_delay=self.cfg.poll_s, max_delay=1.0,
                description="broadcast fetch",
            )
        except (BroadcastCorrupt, OSError, ValueError) as e:
            logger.error(
                "fleet worker %r: broadcast refresh failed (%s) — "
                "keeping policy version %s", self.worker_id, e,
                self._held_version,
            )
            return
        t = self.trainer
        t.params = serde.load_params_like(t.params, arrays)
        t._policy_version = version
        self._held_version = version
        logger.info(
            "fleet worker %r: refreshed weights to policy version %d",
            self.worker_id, version,
        )

    # -- assignments ------------------------------------------------------

    def _scan_assignments(self) -> List[str]:
        try:
            entries = self.transport.list(DISPATCH_DIR)
        except (OSError, ConnectionError):
            return []
        out = []
        for entry in entries:
            if "_a" not in entry:
                continue
            chunk = entry.rsplit("_a", 1)[0]
            if entry in self._done or self._delivered(chunk):
                continue
            out.append(entry)
        return out

    def _delivered(self, chunk: str) -> bool:
        try:
            return (
                self.transport.get_meta(
                    CHUNKS_DIR, chunk, meta_name="chunk.json"
                )
                is not None
            )
        except (OSError, ConnectionError):
            return False

    def _next_assignment(self):
        """The oldest undelivered assignment addressed to this worker
        (highest attempt per chunk wins — an older attempt addressed
        here may have been superseded by a re-dispatch elsewhere)."""
        best: Dict[str, str] = {}
        for entry in self._scan_assignments():
            chunk, attempt = entry.rsplit("_a", 1)
            prev = best.get(chunk)
            if prev is None or int(attempt) > int(prev.rsplit("_a", 1)[1]):
                best[chunk] = entry
        for chunk in sorted(best):
            entry = best[chunk]
            try:
                # route on the meta alone — N idle workers polling every
                # fraction of a second must not each load every
                # in-flight assignment's full prompt arrays off the
                # transport
                meta = self.transport.get_meta(
                    DISPATCH_DIR, entry, meta_name="assignment.json"
                )
                if meta is None or meta.get("worker") != self.worker_id:
                    continue
                msg = self.transport.get(
                    DISPATCH_DIR, entry, meta_name="assignment.json"
                )
            except (OSError, ConnectionError):
                # transient transport outage (tcp hub restart, shared-fs
                # hiccup): the next poll tick retries — a worker must
                # not die for a blip the scan path already tolerates
                return None
            if msg is not None:
                return msg
        return None

    # -- production -------------------------------------------------------

    def _produce(self, meta: Dict[str, Any], arrays) -> None:
        from trlx_tpu.utils import Clock

        t = self.trainer
        chunk_id = tuple(meta["chunk_id"])
        iter_count = int(meta.get("iter_count", 0))
        if t.chaos is not None and t.chaos.consult("fleet_partition"):
            # chaos: network partition — the worker is alive but its
            # beats can't land; the learner must evict + re-dispatch,
            # and this worker's late delivery must dedup away (or land
            # first — bit-identical either way)
            self._beat_pause.set()
            time.sleep(t.chaos.stall_delay)
            self._beat_pause.clear()
        self._refresh_weights()
        snap = serde.snapshot_from_wire(meta["snapshot"], t.rng)
        t._exp_restore_snapshot(snap)
        batch = serde.prompt_batch_from_arrays(
            arrays, meta.get("prompt_metadata")
        )
        stats: Dict[str, Any] = {}
        t0 = time.time()
        gen_out = t.generate(batch.input_ids, batch.attention_mask)
        stats["time/rollout_generate"] = time.time() - t0
        if t.chaos is not None and t.chaos.consult("fleet_worker_death"):
            # chaos: the worker dies MID-CHUNK (generation done, score
            # pending) — a hard exit, so the beat thread dies with it
            # and the learner sees exactly what a real kill looks like
            logger.error(
                "chaos: fleet worker %r dying mid-chunk %s",
                self.worker_id, chunk_id,
            )
            os._exit(3)
        rollout_batch, rows_local = t._score_and_assemble(
            batch, gen_out, stats, iter_count, Clock()
        )
        try:
            delivered = self.transport.put(
                CHUNKS_DIR,
                f"e{chunk_id[0]}_s{chunk_id[1]}",
                {
                    "chunk_id": list(chunk_id),
                    "policy_version": int(self._held_version or 0),
                    "stats": serde.stats_to_wire(stats),
                    "rows_local": int(rows_local),
                    "post_snapshot": serde.snapshot_to_wire(
                        t._exp_snapshot()
                    ),
                    "worker": self.worker_id,
                    "attempt": int(meta.get("attempt", 1)),
                },
                serde.rollout_to_arrays(rollout_batch),
                meta_name="chunk.json",
            )
        except (OSError, ConnectionError) as e:
            # delivery lost to a partition/hub restart: the attempt is
            # NOT marked done, so the next poll re-produces this exact
            # assignment — bit-identical by the replay contract — and
            # re-posts through the dedup
            logger.warning(
                "fleet worker %r: delivery of chunk %s failed (%s); "
                "will regenerate and re-post", self.worker_id, chunk_id, e,
            )
            return
        self._done.add(
            f"e{chunk_id[0]}_s{chunk_id[1]}_a{int(meta.get('attempt', 1))}"
        )
        self._produced += 1
        logger.info(
            "fleet worker %r: chunk %s %s", self.worker_id, chunk_id,
            "delivered" if delivered else
            "already delivered elsewhere (dropped as duplicate)",
        )

    # -- the loop ---------------------------------------------------------

    def run(self) -> int:
        deadline = time.time() + self.cfg.attach_timeout_s
        while not self._sync_membership():
            if shutdown_requested(self.transport):
                return 0
            if time.time() >= deadline:
                logger.error(
                    "fleet worker %r: no learner attached within "
                    "attach_timeout_s=%g — giving up", self.worker_id,
                    self.cfg.attach_timeout_s,
                )
                return 1
            time.sleep(self.cfg.poll_s)
        beat_thread = threading.Thread(
            target=self._beat_loop, name="fleet-beat", daemon=True
        )
        beat_thread.start()
        last_attached = time.time()
        try:
            while True:
                if shutdown_requested(self.transport):
                    logger.info(
                        "fleet worker %r: learner signalled shutdown "
                        "after %d chunks", self.worker_id, self._produced,
                    )
                    return 0
                if self._sync_membership():
                    last_attached = time.time()
                elif (
                    time.time() - last_attached
                    >= self.cfg.detach_timeout_s
                ):
                    # the control plane has been GONE (membership
                    # unreadable/absent) for the whole window: a
                    # learner restart or hub relaunch would have
                    # re-registered us long ago. The likeliest story
                    # is a learner that finished and closed its hosted
                    # hub while our link was partitioned — its
                    # shutdown flag died with the hub — so exit CLEAN:
                    # the delivered chunks are this worker's durable
                    # output either way
                    logger.warning(
                        "fleet worker %r: control plane unreachable "
                        "for detach_timeout_s=%g after %d chunks — "
                        "assuming the learner is gone; exiting clean",
                        self.worker_id, self.cfg.detach_timeout_s,
                        self._produced,
                    )
                    return 0
                assignment = self._next_assignment()
                if assignment is None:
                    time.sleep(self.cfg.poll_s)
                    continue
                self._produce(*assignment)
                if (
                    self.max_chunks is not None
                    and self._produced >= self.max_chunks
                ):
                    logger.info(
                        "fleet worker %r: max_chunks=%d reached",
                        self.worker_id, self.max_chunks,
                    )
                    return 0
        finally:
            self._beat_stop.set()
            beat_thread.join(timeout=2.0)


def run_worker(
    config,
    reward_fn,
    fleet_dir: Optional[str] = None,
    worker_id: Optional[str] = None,
    stop_sequences: Optional[List[str]] = None,
    max_chunks: Optional[int] = None,
) -> int:
    """Build a worker-side trainer from the learner's config and serve
    the fleet until shutdown. Returns a process exit code (0 = clean).

    The tracker is forced off (two processes must not interleave one
    metrics.jsonl) and nothing is ever checkpointed from a worker —
    its durable state is exactly the chunks it delivers.
    """
    from trlx_tpu.parallel import multihost as mh
    from trlx_tpu.utils import set_seed
    from trlx_tpu.utils.loading import get_trainer

    if mh.process_count() > 1:
        raise NotImplementedError(
            "fleet workers are single-process (one worker = one "
            "inference replica); run one worker per host instead"
        )
    fleet_cfg = FleetConfig.from_dict(getattr(config.method, "fleet", None))
    root = fleet_dir or fleet_cfg.resolved_dir(config.train.checkpoint_dir)
    # same seed => same random-init base/reference params as the
    # learner's; the policy side is replaced by the broadcast anyway
    set_seed(config.train.seed)
    # the worker-side trainer must not ATTACH as a learner (no
    # membership-epoch bump, no watchdog monitor thread, no tracker
    # file racing the learner's)
    config = config.evolve(
        train=dict(tracker=None, watchdog=dict(enabled=False)),
        method=dict(fleet=dict(enabled=False)),
    )
    trainer = get_trainer(config.train.trainer)(
        config=config, reward_fn=reward_fn,
        stop_sequences=stop_sequences or [],
    )
    worker = FleetWorker(
        trainer, root, fleet_cfg, worker_id=worker_id,
        max_chunks=max_chunks,
    )
    logger.info(
        "fleet worker %r serving %s", worker.worker_id, root,
    )
    return worker.run()
