"""Worker membership: heartbeat-leased registry + membership epochs.

The fleet's liveness story, built on the same TTL/heartbeat shape as
``exp/leases.py`` but across PROCESS boundaries. All state lives as
RECORDS on the ``exp/net.py Transport`` seam — last-write-wins JSON
documents — so the control plane rides whatever backend the fleet is
configured with: the shared-filesystem default (atomically-written
files, byte-identical to the pre-transport layout) or a tcp hub (no
shared filesystem at all). Either side can die at any byte boundary
and the survivor reads a consistent picture; on tcp, a dead LINK reads
as absent/unchanged and the TTL machinery turns that into eviction +
rejoin rather than an exception.

Record layout (topic, name) — on shared-fs, ``<root>/<topic>/<name>
.json``:

  ("", "membership")     the learner's attach record: a MEMBERSHIP
                         EPOCH bumped every time a learner attaches
                         (fresh start OR supervisor relaunch). Workers
                         poll it and re-register whenever the epoch
                         moves — the handshake that lets a restarted
                         learner re-attach a surviving fleet instead
                         of orphaning it. The SAME handshake covers a
                         hub restart: the flag/epoch records are
                         re-written by the learner's next scan and
                         workers' next beats re-register.
  ("workers", <id>)      one record per worker, rewritten at every
                         heartbeat (``last_beat`` + the epoch the
                         worker registered under + the weight version
                         it holds). A record silent past
                         ``worker_ttl_s`` is EVICTED: removed, its
                         in-flight chunk re-dispatched, and a flap
                         recorded. A PARTITIONED worker looks exactly
                         like a dead one — silent — which is the
                         point: detection is uniform.
  ("quarantine", <id>)   learner-side verdict on a flapping worker
                         (``flap_limit`` evictions in a row): excluded
                         from dispatch until ``until``, with the
                         backoff DOUBLING per repeat quarantine.
                         Expiry re-admits.
  ("", "shutdown")       clean-finish flag: workers exit 0 when it
                         appears (a crashed/stalled learner never
                         writes it, so the fleet survives for the
                         relaunch).

Clocks are injectable (tier-1 drives eviction/quarantine on a fake
clock); the cross-process default is ``time.time`` — wall clock,
because the records are read by OTHER processes (``time.monotonic`` is
process-local).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Union

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

# legacy shared-fs names; the record topology below maps onto them
# exactly (topic "" = the fleet root itself)
MEMBERSHIP_FILE = "membership.json"
SHUTDOWN_FILE = "shutdown.json"
WORKERS_DIR = "workers"
QUARANTINE_DIR = "quarantine"

MEMBERSHIP_RECORD = "membership"
SHUTDOWN_RECORD = "shutdown"
WORKERS_TOPIC = "workers"
QUARANTINE_TOPIC = "quarantine"

Control = Union[str, "Transport"]  # noqa: F821 — forward ref, see as_control


def as_control(control: Control):
    """Coerce a fleet-root path into the golden shared-fs transport;
    pass a real :class:`~trlx_tpu.exp.net.Transport` through. Keeps
    every pre-transport call site (``read_membership(root)``, tests,
    bench) working unchanged."""
    if isinstance(control, str):
        from trlx_tpu.exp.net import SharedFSTransport

        return SharedFSTransport(control)
    return control


def read_membership(control: Control) -> Optional[Dict[str, Any]]:
    """The learner's attach record, or None when absent OR unreachable
    (a worker mid-partition keeps its current epoch and retries)."""
    try:
        return as_control(control).get_record("", MEMBERSHIP_RECORD)
    except (OSError, ConnectionError):
        return None


def shutdown_requested(control: Control) -> bool:
    """True only on a POSITIVE read of the clean-finish flag — an
    unreachable control plane must not look like a shutdown order."""
    try:
        return (
            as_control(control).get_record("", SHUTDOWN_RECORD) is not None
        )
    except (OSError, ConnectionError):
        return False


def write_worker_record(
    control: Control,
    worker_id: str,
    epoch: int,
    weights_version: Optional[int],
    clock: Callable[[], float] = time.time,
    joined_at: Optional[float] = None,
) -> None:
    """Register/heartbeat in one record rewrite (registration IS the
    first heartbeat; a rejoin after eviction or a hub restart is just
    the next one). Raises on an unreachable control plane — the beat
    loop swallows and retries on its own cadence."""
    now = clock()
    as_control(control).put_record(
        WORKERS_TOPIC, worker_id,
        {
            "worker": worker_id,
            "epoch": int(epoch),
            "last_beat": now,
            "joined_at": now if joined_at is None else joined_at,
            "weights_version": weights_version,
            "pid": os.getpid(),
        },
    )


class WorkerRegistry:
    """The learner-side view: membership epochs, liveness, eviction and
    flap quarantine. One instance per attached learner. ``root`` may be
    a fleet-directory path (golden shared-fs) or any Transport; every
    read degrades to empty/False under a control-plane outage so a
    partition trips the fleet's degrade ladder, not an exception."""

    def __init__(
        self,
        root: Control,
        worker_ttl_s: float,
        flap_limit: int = 3,
        flap_backoff_s: float = 5.0,
        clock: Callable[[], float] = time.time,
    ):
        self.control = as_control(root)
        self.root = root if isinstance(root, str) else None
        self.worker_ttl_s = float(worker_ttl_s)
        self.flap_limit = int(flap_limit)
        self.flap_backoff_s = float(flap_backoff_s)
        self._clock = clock
        # golden layout: the workers/ and quarantine/ dirs exist from
        # attach even before the first record lands
        if self.root is not None:
            os.makedirs(os.path.join(self.root, WORKERS_DIR), exist_ok=True)
            os.makedirs(
                os.path.join(self.root, QUARANTINE_DIR), exist_ok=True
            )
        self.epoch = 0
        # flap accounting is learner-side in-memory state: an eviction
        # streak per worker, and how many quarantines it has served
        # (the backoff doubles per served quarantine)
        self._flap_streak: Dict[str, int] = {}
        self._quarantines_served: Dict[str, int] = {}
        self.stats: Dict[str, int] = {
            "evictions": 0,
            "quarantines": 0,
            "readmissions": 0,
        }

    # -- membership epoch (learner attach/re-attach handshake) -----------

    def open_epoch(self, learner: str = "learner") -> int:
        """Attach this learner: bump the membership epoch. Every worker
        registered under an older epoch re-registers when it sees the
        bump — the re-attach handshake that survives a supervisor
        relaunch (exit 87 path) without orphaning the fleet. Raises if
        the control plane is unreachable: a learner that cannot attach
        must not pretend it did."""
        prev = read_membership(self.control)
        self.epoch = int(prev.get("epoch", 0)) + 1 if prev else 1
        self.control.put_record(
            "", MEMBERSHIP_RECORD,
            {"epoch": self.epoch, "learner": learner,
             "stamped_at": self._clock()},
        )
        # a previous clean finish must not make re-attached workers exit
        try:
            self.control.delete_record("", SHUTDOWN_RECORD)
        except (OSError, ConnectionError):
            pass
        logger.info(
            "fleet membership: learner %r opened epoch %d", learner,
            self.epoch,
        )
        return self.epoch

    # -- liveness ---------------------------------------------------------

    def worker_records(self) -> Dict[str, Dict[str, Any]]:
        try:
            names = self.control.list_records(WORKERS_TOPIC)
        except (OSError, ConnectionError):
            return {}
        out: Dict[str, Dict[str, Any]] = {}
        for name in sorted(names):
            try:
                rec = self.control.get_record(WORKERS_TOPIC, name)
            except (OSError, ConnectionError):
                rec = None
            if rec and "worker" in rec:
                out[rec["worker"]] = rec
        return out

    def live_workers(self) -> List[str]:
        """Workers registered under the CURRENT epoch, beating within
        the TTL, and not quarantined — the dispatchable set."""
        now = self._clock()
        return [
            wid
            for wid, rec in self.worker_records().items()
            if rec.get("epoch") == self.epoch
            and now - rec.get("last_beat", 0.0) <= self.worker_ttl_s
            and not self.is_quarantined(wid)
        ]

    def evict_silent(self) -> List[str]:
        """Remove current-epoch records whose heartbeat is older than
        the TTL (worker death, partition, wedge) and record a flap for
        each. The caller re-dispatches any chunk the evicted worker
        held. Stale-epoch records are garbage-collected silently (the
        worker either re-registers or is gone)."""
        now = self._clock()
        evicted = []
        for wid, rec in self.worker_records().items():
            age = now - rec.get("last_beat", 0.0)
            if age <= self.worker_ttl_s:
                continue
            try:
                self.control.delete_record(WORKERS_TOPIC, wid)
            except (OSError, ConnectionError):
                continue
            if rec.get("epoch") != self.epoch:
                continue  # stale-epoch leftover, not a live-fleet flap
            evicted.append(wid)
            self.stats["evictions"] += 1
            self._record_flap(wid)
            logger.warning(
                "fleet membership: evicted worker %r (silent %.3gs > "
                "ttl %.3gs)", wid, age, self.worker_ttl_s,
            )
        return evicted

    def evict(self, worker_id: str, reason: str) -> bool:
        """Force-evict one worker (the dispatch-timeout backstop: alive
        and beating but not producing). Flap-tracked like a silent
        eviction; the worker's next beat re-registers it (rejoin)."""
        try:
            if self.control.get_record(WORKERS_TOPIC, worker_id) is None:
                return False
            self.control.delete_record(WORKERS_TOPIC, worker_id)
        except (OSError, ConnectionError):
            return False
        self.stats["evictions"] += 1
        self._record_flap(worker_id)
        logger.warning(
            "fleet membership: force-evicted worker %r (%s)",
            worker_id, reason,
        )
        return True

    # -- flap quarantine --------------------------------------------------

    def _record_flap(self, worker_id: str) -> None:
        streak = self._flap_streak.get(worker_id, 0) + 1
        self._flap_streak[worker_id] = streak
        if streak < self.flap_limit:
            return
        served = self._quarantines_served.get(worker_id, 0)
        backoff = self.flap_backoff_s * (2 ** served)
        self._quarantines_served[worker_id] = served + 1
        self._flap_streak[worker_id] = 0  # streak restarts post-quarantine
        self.stats["quarantines"] += 1
        try:
            self.control.put_record(
                QUARANTINE_TOPIC, worker_id,
                {"worker": worker_id, "until": self._clock() + backoff,
                 "flaps": streak, "backoff_s": backoff},
            )
        except (OSError, ConnectionError):
            logger.error(
                "fleet membership: quarantine record for %r not "
                "persisted (control plane unreachable)", worker_id,
            )
        logger.error(
            "fleet membership: worker %r QUARANTINED for %.3gs (%d "
            "evictions in a row >= flap_limit %d); re-admitted with "
            "doubled backoff on the next quarantine", worker_id, backoff,
            streak, self.flap_limit,
        )

    def note_healthy(self, worker_id: str) -> None:
        """A consumed delivery from this worker breaks its eviction
        streak: ``flap_limit`` evictions IN A ROW means consecutive.
        Without the reset, unrelated transient evictions hours apart
        would accumulate and eventually quarantine a healthy worker
        with ever-doubling backoff."""
        if self._flap_streak.get(worker_id):
            self._flap_streak[worker_id] = 0

    def is_quarantined(self, worker_id: str) -> bool:
        """Quarantine verdict, with expiry = re-admission (the record
        is removed so a re-admitted worker reads as clean). An
        unreachable control plane reads as not-quarantined — liveness
        gating already keeps an unreachable fleet out of dispatch."""
        try:
            rec = self.control.get_record(QUARANTINE_TOPIC, worker_id)
        except (OSError, ConnectionError):
            return False
        if rec is None:
            return False
        if self._clock() >= rec.get("until", 0.0):
            try:
                self.control.delete_record(QUARANTINE_TOPIC, worker_id)
            except (OSError, ConnectionError):
                pass
            self.stats["readmissions"] += 1
            logger.warning(
                "fleet membership: quarantine on worker %r expired — "
                "re-admitted", worker_id,
            )
            return False
        return True

    # -- shutdown ---------------------------------------------------------

    def shutdown(self, reason: str = "clean finish") -> None:
        """Clean-finish flag: workers exit 0 when they see it. A
        crashed or stalled learner never writes this, so a surviving
        fleet waits for the relaunch's epoch bump instead."""
        try:
            self.control.put_record(
                "", SHUTDOWN_RECORD,
                {"reason": reason, "stamped_at": self._clock()},
            )
        except (OSError, ConnectionError):
            logger.error(
                "fleet membership: shutdown flag not persisted (control "
                "plane unreachable); workers will idle to attach_timeout"
            )
