"""Worker membership: heartbeat-leased registry + membership epochs.

The fleet's liveness story, built on the same TTL/heartbeat shape as
``exp/leases.py`` but across PROCESS boundaries: all state lives as
atomically-written JSON records under the fleet directory (a shared
filesystem is the one channel a TPU pod always has), so either side can
die at any byte boundary and the survivor reads a consistent picture.

  membership.json    the learner's attach record: a MEMBERSHIP EPOCH
                     bumped every time a learner attaches (fresh start
                     OR supervisor relaunch). Workers poll it and
                     re-register whenever the epoch moves — the
                     handshake that lets a restarted learner re-attach
                     a surviving fleet instead of orphaning it.
  workers/<id>.json  one record per worker, rewritten atomically at
                     every heartbeat (``last_beat`` + the epoch the
                     worker registered under + the weight version it
                     holds). A record silent past ``worker_ttl_s`` is
                     EVICTED: removed, its in-flight chunk
                     re-dispatched, and a flap recorded.
  quarantine/<id>.json  learner-side verdict on a flapping worker
                     (``flap_limit`` evictions in a row): excluded
                     from dispatch until ``until``, with the backoff
                     DOUBLING per repeat quarantine. Expiry re-admits.
  shutdown.json      clean-finish flag: workers exit 0 when it
                     appears (a crashed/stalled learner never writes
                     it, so the fleet survives for the relaunch).

Clocks are injectable (tier-1 drives eviction/quarantine on a fake
clock); the cross-process default is ``time.time`` — wall clock,
because the records are read by OTHER processes (``time.monotonic`` is
process-local).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional

from trlx_tpu.utils import logging
from trlx_tpu.utils.checkpointing import atomic_json_write

logger = logging.get_logger(__name__)

MEMBERSHIP_FILE = "membership.json"
SHUTDOWN_FILE = "shutdown.json"
WORKERS_DIR = "workers"
QUARANTINE_DIR = "quarantine"


def _read_json(path: str) -> Optional[Dict[str, Any]]:
    """Parse-safe read: a torn/missing record reads as absent (the
    writer side is atomic, so this only covers a reader racing the
    very first write)."""
    import json

    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def read_membership(root: str) -> Optional[Dict[str, Any]]:
    return _read_json(os.path.join(root, MEMBERSHIP_FILE))


def shutdown_requested(root: str) -> bool:
    return os.path.isfile(os.path.join(root, SHUTDOWN_FILE))


def write_worker_record(
    root: str,
    worker_id: str,
    epoch: int,
    weights_version: Optional[int],
    clock: Callable[[], float] = time.time,
    joined_at: Optional[float] = None,
) -> None:
    """Register/heartbeat in one atomic rewrite (registration IS the
    first heartbeat; a rejoin after eviction is just the next one)."""
    now = clock()
    atomic_json_write(
        os.path.join(root, WORKERS_DIR, f"{worker_id}.json"),
        {
            "worker": worker_id,
            "epoch": int(epoch),
            "last_beat": now,
            "joined_at": now if joined_at is None else joined_at,
            "weights_version": weights_version,
            "pid": os.getpid(),
        },
    )


class WorkerRegistry:
    """The learner-side view: membership epochs, liveness, eviction and
    flap quarantine. One instance per attached learner."""

    def __init__(
        self,
        root: str,
        worker_ttl_s: float,
        flap_limit: int = 3,
        flap_backoff_s: float = 5.0,
        clock: Callable[[], float] = time.time,
    ):
        self.root = root
        self.worker_ttl_s = float(worker_ttl_s)
        self.flap_limit = int(flap_limit)
        self.flap_backoff_s = float(flap_backoff_s)
        self._clock = clock
        os.makedirs(os.path.join(root, WORKERS_DIR), exist_ok=True)
        os.makedirs(os.path.join(root, QUARANTINE_DIR), exist_ok=True)
        self.epoch = 0
        # flap accounting is learner-side in-memory state: an eviction
        # streak per worker, and how many quarantines it has served
        # (the backoff doubles per served quarantine)
        self._flap_streak: Dict[str, int] = {}
        self._quarantines_served: Dict[str, int] = {}
        self.stats: Dict[str, int] = {
            "evictions": 0,
            "quarantines": 0,
            "readmissions": 0,
        }

    # -- membership epoch (learner attach/re-attach handshake) -----------

    def open_epoch(self, learner: str = "learner") -> int:
        """Attach this learner: bump the membership epoch. Every worker
        registered under an older epoch re-registers when it sees the
        bump — the re-attach handshake that survives a supervisor
        relaunch (exit 87 path) without orphaning the fleet."""
        prev = read_membership(self.root)
        self.epoch = int(prev.get("epoch", 0)) + 1 if prev else 1
        atomic_json_write(
            os.path.join(self.root, MEMBERSHIP_FILE),
            {"epoch": self.epoch, "learner": learner,
             "stamped_at": self._clock()},
        )
        # a previous clean finish must not make re-attached workers exit
        try:
            os.remove(os.path.join(self.root, SHUTDOWN_FILE))
        except OSError:
            pass
        logger.info(
            "fleet membership: learner %r opened epoch %d", learner,
            self.epoch,
        )
        return self.epoch

    # -- liveness ---------------------------------------------------------

    def worker_records(self) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        wdir = os.path.join(self.root, WORKERS_DIR)
        for entry in sorted(os.listdir(wdir)):
            if not entry.endswith(".json"):
                continue
            rec = _read_json(os.path.join(wdir, entry))
            if rec and "worker" in rec:
                out[rec["worker"]] = rec
        return out

    def live_workers(self) -> List[str]:
        """Workers registered under the CURRENT epoch, beating within
        the TTL, and not quarantined — the dispatchable set."""
        now = self._clock()
        return [
            wid
            for wid, rec in self.worker_records().items()
            if rec.get("epoch") == self.epoch
            and now - rec.get("last_beat", 0.0) <= self.worker_ttl_s
            and not self.is_quarantined(wid)
        ]

    def evict_silent(self) -> List[str]:
        """Remove current-epoch records whose heartbeat is older than
        the TTL (worker death, partition, wedge) and record a flap for
        each. The caller re-dispatches any chunk the evicted worker
        held. Stale-epoch records are garbage-collected silently (the
        worker either re-registers or is gone)."""
        now = self._clock()
        evicted = []
        for wid, rec in self.worker_records().items():
            age = now - rec.get("last_beat", 0.0)
            if age <= self.worker_ttl_s:
                continue
            try:
                os.remove(
                    os.path.join(self.root, WORKERS_DIR, f"{wid}.json")
                )
            except OSError:
                continue
            if rec.get("epoch") != self.epoch:
                continue  # stale-epoch leftover, not a live-fleet flap
            evicted.append(wid)
            self.stats["evictions"] += 1
            self._record_flap(wid)
            logger.warning(
                "fleet membership: evicted worker %r (silent %.3gs > "
                "ttl %.3gs)", wid, age, self.worker_ttl_s,
            )
        return evicted

    def evict(self, worker_id: str, reason: str) -> bool:
        """Force-evict one worker (the dispatch-timeout backstop: alive
        and beating but not producing). Flap-tracked like a silent
        eviction; the worker's next beat re-registers it (rejoin)."""
        try:
            os.remove(
                os.path.join(self.root, WORKERS_DIR, f"{worker_id}.json")
            )
        except OSError:
            return False
        self.stats["evictions"] += 1
        self._record_flap(worker_id)
        logger.warning(
            "fleet membership: force-evicted worker %r (%s)",
            worker_id, reason,
        )
        return True

    # -- flap quarantine --------------------------------------------------

    def _quarantine_path(self, worker_id: str) -> str:
        return os.path.join(self.root, QUARANTINE_DIR, f"{worker_id}.json")

    def _record_flap(self, worker_id: str) -> None:
        streak = self._flap_streak.get(worker_id, 0) + 1
        self._flap_streak[worker_id] = streak
        if streak < self.flap_limit:
            return
        served = self._quarantines_served.get(worker_id, 0)
        backoff = self.flap_backoff_s * (2 ** served)
        self._quarantines_served[worker_id] = served + 1
        self._flap_streak[worker_id] = 0  # streak restarts post-quarantine
        self.stats["quarantines"] += 1
        atomic_json_write(
            self._quarantine_path(worker_id),
            {"worker": worker_id, "until": self._clock() + backoff,
             "flaps": streak, "backoff_s": backoff},
        )
        logger.error(
            "fleet membership: worker %r QUARANTINED for %.3gs (%d "
            "evictions in a row >= flap_limit %d); re-admitted with "
            "doubled backoff on the next quarantine", worker_id, backoff,
            streak, self.flap_limit,
        )

    def note_healthy(self, worker_id: str) -> None:
        """A consumed delivery from this worker breaks its eviction
        streak: ``flap_limit`` evictions IN A ROW means consecutive.
        Without the reset, unrelated transient evictions hours apart
        would accumulate and eventually quarantine a healthy worker
        with ever-doubling backoff."""
        if self._flap_streak.get(worker_id):
            self._flap_streak[worker_id] = 0

    def is_quarantined(self, worker_id: str) -> bool:
        """Quarantine verdict, with expiry = re-admission (the record
        is removed so a re-admitted worker reads as clean)."""
        rec = _read_json(self._quarantine_path(worker_id))
        if rec is None:
            return False
        if self._clock() >= rec.get("until", 0.0):
            try:
                os.remove(self._quarantine_path(worker_id))
            except OSError:
                pass
            self.stats["readmissions"] += 1
            logger.warning(
                "fleet membership: quarantine on worker %r expired — "
                "re-admitted", worker_id,
            )
            return False
        return True

    # -- shutdown ---------------------------------------------------------

    def shutdown(self, reason: str = "clean finish") -> None:
        """Clean-finish flag: workers exit 0 when they see it. A
        crashed or stalled learner never writes this, so a surviving
        fleet waits for the relaunch's epoch bump instead."""
        atomic_json_write(
            os.path.join(self.root, SHUTDOWN_FILE),
            {"reason": reason, "stamped_at": self._clock()},
        )
