"""Fault-tolerant disaggregated rollout fleet (ROADMAP item 1's
remote-producer half, on the PR 7 experience-transport substrate).

  config.py       parsed ``ppo.fleet.*`` (default off; requires
                  ``ppo.exp.enabled``).
  membership.py   worker registry: heartbeat-leased records, membership
                  epochs (learner attach/re-attach handshake), eviction
                  of silent workers, flap quarantine with doubling
                  backoff.
  broadcast.py    versioned weight broadcast: atomic snapshot publish
                  with per-file sha256 manifests; workers verify before
                  adopting and KEEP the previous version on corruption
                  (broadcast failure degrades to off-policy data the
                  ``exp.staleness`` gate corrects).
  coordinator.py  learner side: chunk dispatch/collect, worker-level
                  TTL watching, re-dispatch with the replay snapshot
                  (bit-identical regeneration), degraded-mode verdicts
                  (below ``fleet.min_workers`` -> the ``fleet``
                  guardrail signal + in-process fallback).
  worker.py       the cross-process rollout worker (``run_worker``):
                  a learner-less PPO trainer driven by dispatch
                  messages, sharing ``_score_and_assemble`` verbatim.
  serde.py        exact pytree <-> numpy wire conversions + atomic
                  message-directory commits.

``membership``/``broadcast``/``config`` are jax-free host modules;
import ``coordinator``/``worker``/``serde`` directly where needed.
"""

from trlx_tpu.fleet.broadcast import BroadcastCorrupt, WeightBroadcast
from trlx_tpu.fleet.config import FleetConfig
from trlx_tpu.fleet.membership import WorkerRegistry

__all__ = [
    "BroadcastCorrupt",
    "FleetConfig",
    "WeightBroadcast",
    "WorkerRegistry",
]
