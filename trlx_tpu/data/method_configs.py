"""Method (algorithm) hyperparameter configs and their registry.

Parity: /root/reference/trlx/data/method_configs.py:9-56 (registry semantics),
/root/reference/trlx/models/modeling_ppo.py:73-238 (PPOConfig fields),
/root/reference/trlx/models/modeling_ilql.py:48-93 (ILQLConfig fields),
/root/reference/trlx/trainer/accelerate_sft_trainer.py:16-26 (SFTConfig),
/root/reference/trlx/trainer/accelerate_rft_trainer.py:18-44 (RFTConfig).

Unlike the reference, the loss functions themselves are pure jittable
functions in :mod:`trlx_tpu.ops`; the dataclasses here only carry
hyperparameters (and thin `.loss` delegates for API familiarity).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

_METHODS: Dict[str, type] = {}


def register_method(name_or_cls):
    """Register a method config class under a lowercase name (decorator)."""

    def _register(cls, name: str):
        _METHODS[name.lower()] = cls
        return cls

    if isinstance(name_or_cls, str):
        return lambda cls: _register(cls, name_or_cls)
    return _register(name_or_cls, name_or_cls.__name__)


def get_method(name: str) -> type:
    try:
        return _METHODS[name.lower()]
    except KeyError:
        raise ValueError(
            f"Unknown method {name!r}; registered: {sorted(_METHODS)}"
        ) from None


def _fields_only(cls, config: Dict[str, Any]) -> Dict[str, Any]:
    known = {f.name for f in dataclasses.fields(cls)}
    unknown = set(config) - known
    if unknown:
        raise ValueError(f"{cls.__name__}: unknown config keys {sorted(unknown)}")
    return {k: v for k, v in config.items() if k in known}


@dataclass
@register_method
class MethodConfig:
    """Base config for an RL method; `name` selects the registry entry."""

    name: str

    @classmethod
    def from_dict(cls, config: Dict[str, Any]):
        return cls(**_fields_only(cls, config))

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclass
@register_method
class PPOConfig(MethodConfig):
    """PPO hyperparameters (field parity with reference modeling_ppo.py:73-238)."""

    ppo_epochs: int = 4
    num_rollouts: int = 128
    chunk_size: int = 128
    init_kl_coef: float = 0.05
    target: Optional[float] = None
    horizon: int = 10000
    gamma: float = 1.0
    lam: float = 0.95
    cliprange: float = 0.2
    cliprange_value: float = 0.2
    vf_coef: float = 1.0
    scale_reward: Optional[str] = "ignored"
    ref_mean: Optional[float] = None
    ref_std: Optional[float] = None
    cliprange_reward: float = 10.0
    gen_kwargs: dict = field(default_factory=lambda: dict(max_new_tokens=40))
    gen_experience_kwargs: Optional[dict] = None
    num_value_layers_unfrozen: int = 0
    # Cycle-level rollout/optimization overlap: dispatch the first chunk
    # of cycle t+1's generation AHEAD of cycle t's fused optimization
    # block (device FIFO samples it first; the host decodes+scores it
    # while the block trains). The samples are one policy update stale,
    # which PPO's importance ratio absorbs — old_logprobs are recomputed
    # by the teacher-forced scorer with the params the optimization
    # epoch actually starts from, so the ratio stays self-consistent.
    # Preemption/resume cursors account for the in-flight chunk (it
    # rewinds if it never trains). Requires the scanned epoch path
    # (train.fused_inner_loop); off by default.
    overlap_rollouts: bool = False
    # Serving-grade rollout decode engine (models/gen_engine.py):
    # continuous batching over a paged int8 KV cache with optional
    # reference-drafted speculative decoding. Parsed by
    # gen_engine.GenEngineConfig (enabled/slots/page_size/paged/
    # pool_pages/refill_width/spec_decode/draft_k/kv_quant). Default {}
    # = disabled: rollouts keep the static whole-batch sampler. When
    # enabled, each generate() chunk runs through slot-based decode
    # (finished rows are refilled from the remaining prompts of the
    # chunk), and the engine's RNG is keyed per (prompt, position) —
    # sampled continuations differ from the static sampler's stream but
    # are invariant to slot assignment/batch composition (golden-checked
    # in tests/test_gen_engine.py). Composes with overlap_rollouts and
    # the preemption/rewind cursors unchanged: the engine sits behind
    # the same per-chunk generate() seam both already drive.
    gen_engine: dict = field(default_factory=dict)
    # Resilient experience transport (trlx_tpu/exp/): route rollout
    # chunks through a durable queue with at-least-once delivery —
    # lease-based production (an expired lease re-dispatches the chunk
    # to a live producer), consumer-side dedup, back-pressure past
    # exp.max_depth, a persisted consumer cursor (state.json, inside
    # the atomic checkpoint) and a staleness admission gate
    # (exp.staleness.mode: reject|clip, default reject at staleness>1;
    # clip threads IMPACT-style per-token importance weights into the
    # surrogate). Parsed by exp.queue.ExpConfig (enabled/max_depth/
    # lease_ttl_s/offer_timeout_s/wait_poll_s/staleness). Default {} =
    # disabled; enabled and fault-free it is golden-checked bit-equal
    # (losses + consumed prompt order) to the direct rollout path.
    # This is the substrate for the disaggregated actor-learner split
    # (ROADMAP item 1): remote producers plug in behind the same
    # transport the in-process loop chaos-proves.
    exp: dict = field(default_factory=dict)
    # Fault-tolerant rollout-worker fleet (trlx_tpu/fleet/): route
    # chunk PRODUCTION to cross-process workers behind the transport
    # seam — worker membership with heartbeat leases + membership
    # epochs (a restarted learner re-attaches surviving workers),
    # versioned weight broadcast with sha256 manifests (a corrupt
    # snapshot is rejected and the previous version kept; stale chunks
    # flow through exp.staleness), flap quarantine with doubling
    # backoff, and degraded-mode fallback to the in-process path (the
    # `fleet` guardrail signal) when live workers drop below
    # fleet.min_workers. Parsed by fleet.config.FleetConfig (enabled/
    # dir/min_workers/worker_ttl_s/flap_limit/...). Default {} =
    # disabled; requires ppo.exp.enabled; fault-free it is golden-
    # checked bit-equal to the in-process exp path.
    fleet: dict = field(default_factory=dict)

    def get_advantages_and_returns(self, values, rewards, response_length, use_whitening=True):
        from trlx_tpu.ops.ppo import gae_advantages_and_returns

        return gae_advantages_and_returns(
            values, rewards, gamma=self.gamma, lam=self.lam, use_whitening=use_whitening
        )

    def loss(self, logprobs, values, old_logprobs, old_values, advantages, returns, mask):
        from trlx_tpu.ops.ppo import ppo_loss

        return ppo_loss(
            logprobs, values, old_logprobs, old_values, advantages, returns, mask,
            cliprange=self.cliprange, cliprange_value=self.cliprange_value,
            vf_coef=self.vf_coef,
        )


@dataclass
@register_method
class ILQLConfig(MethodConfig):
    """ILQL hyperparameters (field parity with reference modeling_ilql.py:48-93)."""

    tau: float = 0.7
    gamma: float = 0.99
    cql_scale: float = 0.1
    awac_scale: float = 1.0
    alpha: float = 0.001
    beta: float = 0.0
    steps_for_target_q_sync: int = 5
    two_qs: bool = True
    gen_kwargs: dict = field(default_factory=lambda: dict(max_new_tokens=56, top_k=20, beta=1.0))

    def loss(self, outputs, labels):
        from trlx_tpu.ops.ilql import ilql_loss

        logits, (qs, target_qs, vs) = outputs
        return ilql_loss(
            logits, qs, target_qs, vs, labels,
            tau=self.tau, gamma=self.gamma, cql_scale=self.cql_scale,
            awac_scale=self.awac_scale, beta=self.beta, two_qs=self.two_qs,
        )


@dataclass
@register_method
class SFTConfig(MethodConfig):
    """SFT hyperparameters (parity: accelerate_sft_trainer.py:16-26)."""

    gen_kwargs: dict = field(default_factory=lambda: dict(max_new_tokens=40))


@dataclass
@register_method
class RFTConfig(MethodConfig):
    """Rejection-sampling fine-tuning (parity: accelerate_rft_trainer.py:18-44)."""

    gen_kwargs: dict = field(default_factory=lambda: dict(max_new_tokens=40))
    start_percentile: float = 0.7
    end_percentile: float = 0.95
    n_improve_steps: int = 4
    n_generations_per_prompt: int = 32
