"""Method (algorithm) hyperparameter configs and their registry.

Parity: /root/reference/trlx/data/method_configs.py:9-56 (registry semantics),
/root/reference/trlx/models/modeling_ppo.py:73-238 (PPOConfig fields),
/root/reference/trlx/models/modeling_ilql.py:48-93 (ILQLConfig fields),
/root/reference/trlx/trainer/accelerate_sft_trainer.py:16-26 (SFTConfig),
/root/reference/trlx/trainer/accelerate_rft_trainer.py:18-44 (RFTConfig).

Unlike the reference, the loss functions themselves are pure jittable
functions in :mod:`trlx_tpu.ops`; the dataclasses here only carry
hyperparameters (and thin `.loss` delegates for API familiarity).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

_METHODS: Dict[str, type] = {}


def register_method(name_or_cls):
    """Register a method config class under a lowercase name (decorator).

    A duplicate name raises: two configs silently shadowing each other
    under one key is exactly the bug a registry exists to prevent.
    Re-registering the SAME class is a no-op (module reloads)."""

    def _register(cls, name: str):
        key = name.lower()
        existing = _METHODS.get(key)
        if existing is not None and (
            (existing.__module__, existing.__qualname__)
            != (cls.__module__, cls.__qualname__)
        ):
            raise ValueError(
                f"method config {name!r} is already registered to "
                f"{existing.__module__}.{existing.__qualname__}; refusing "
                "to overwrite it silently — pick a distinct name"
            )
        _METHODS[key] = cls
        return cls

    if isinstance(name_or_cls, str):
        return lambda cls: _register(cls, name_or_cls)
    return _register(name_or_cls, name_or_cls.__name__)


def get_method(name: str) -> type:
    try:
        return _METHODS[name.lower()]
    except KeyError:
        raise ValueError(
            f"Unknown method {name!r}; registered: {sorted(_METHODS)}"
        ) from None


def _fields_only(cls, config: Dict[str, Any]) -> Dict[str, Any]:
    known = {f.name for f in dataclasses.fields(cls)}
    unknown = set(config) - known
    if unknown:
        raise ValueError(f"{cls.__name__}: unknown config keys {sorted(unknown)}")
    return {k: v for k, v in config.items() if k in known}


@dataclass
@register_method
class MethodConfig:
    """Base config for an RL method; `name` selects the registry entry."""

    name: str

    @classmethod
    def from_dict(cls, config: Dict[str, Any]):
        return cls(**_fields_only(cls, config))

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclass
@register_method
class PPOConfig(MethodConfig):
    """PPO hyperparameters (field parity with reference modeling_ppo.py:73-238)."""

    ppo_epochs: int = 4
    num_rollouts: int = 128
    chunk_size: int = 128
    init_kl_coef: float = 0.05
    target: Optional[float] = None
    horizon: int = 10000
    gamma: float = 1.0
    lam: float = 0.95
    cliprange: float = 0.2
    cliprange_value: float = 0.2
    vf_coef: float = 1.0
    scale_reward: Optional[str] = "ignored"
    ref_mean: Optional[float] = None
    ref_std: Optional[float] = None
    cliprange_reward: float = 10.0
    gen_kwargs: dict = field(default_factory=lambda: dict(max_new_tokens=40))
    gen_experience_kwargs: Optional[dict] = None
    num_value_layers_unfrozen: int = 0
    # Cycle-level rollout/optimization overlap: dispatch the first chunk
    # of cycle t+1's generation AHEAD of cycle t's fused optimization
    # block (device FIFO samples it first; the host decodes+scores it
    # while the block trains). The samples are one policy update stale,
    # which PPO's importance ratio absorbs — old_logprobs are recomputed
    # by the teacher-forced scorer with the params the optimization
    # epoch actually starts from, so the ratio stays self-consistent.
    # Preemption/resume cursors account for the in-flight chunk (it
    # rewinds if it never trains). Requires the scanned epoch path
    # (train.fused_inner_loop); off by default.
    overlap_rollouts: bool = False
    # Serving-grade rollout decode engine (models/gen_engine.py):
    # continuous batching over a paged int8 KV cache with optional
    # reference-drafted speculative decoding. Parsed by
    # gen_engine.GenEngineConfig (enabled/slots/page_size/paged/
    # pool_pages/refill_width/spec_decode/draft_k/kv_quant). Default {}
    # = disabled: rollouts keep the static whole-batch sampler. When
    # enabled, each generate() chunk runs through slot-based decode
    # (finished rows are refilled from the remaining prompts of the
    # chunk), and the engine's RNG is keyed per (prompt, position) —
    # sampled continuations differ from the static sampler's stream but
    # are invariant to slot assignment/batch composition (golden-checked
    # in tests/test_gen_engine.py). Composes with overlap_rollouts and
    # the preemption/rewind cursors unchanged: the engine sits behind
    # the same per-chunk generate() seam both already drive.
    gen_engine: dict = field(default_factory=dict)
    # Resilient experience transport (trlx_tpu/exp/): route rollout
    # chunks through a durable queue with at-least-once delivery —
    # lease-based production (an expired lease re-dispatches the chunk
    # to a live producer), consumer-side dedup, back-pressure past
    # exp.max_depth, a persisted consumer cursor (state.json, inside
    # the atomic checkpoint) and a staleness admission gate
    # (exp.staleness.mode: reject|clip, default reject at staleness>1;
    # clip threads IMPACT-style per-token importance weights into the
    # surrogate). Parsed by exp.queue.ExpConfig (enabled/max_depth/
    # lease_ttl_s/offer_timeout_s/wait_poll_s/staleness). Default {} =
    # disabled; enabled and fault-free it is golden-checked bit-equal
    # (losses + consumed prompt order) to the direct rollout path.
    # This is the substrate for the disaggregated actor-learner split
    # (ROADMAP item 1): remote producers plug in behind the same
    # transport the in-process loop chaos-proves.
    exp: dict = field(default_factory=dict)
    # Fault-tolerant rollout-worker fleet (trlx_tpu/fleet/): route
    # chunk PRODUCTION to cross-process workers behind the transport
    # seam — worker membership with heartbeat leases + membership
    # epochs (a restarted learner re-attaches surviving workers),
    # versioned weight broadcast with sha256 manifests (a corrupt
    # snapshot is rejected and the previous version kept; stale chunks
    # flow through exp.staleness), flap quarantine with doubling
    # backoff, and degraded-mode fallback to the in-process path (the
    # `fleet` guardrail signal) when live workers drop below
    # fleet.min_workers. Parsed by fleet.config.FleetConfig (enabled/
    # dir/min_workers/worker_ttl_s/flap_limit/...). Default {} =
    # disabled; requires ppo.exp.enabled; fault-free it is golden-
    # checked bit-equal to the in-process exp path.
    fleet: dict = field(default_factory=dict)

    def get_advantages_and_returns(self, values, rewards, response_length, use_whitening=True):
        from trlx_tpu.ops.ppo import gae_advantages_and_returns

        return gae_advantages_and_returns(
            values, rewards, gamma=self.gamma, lam=self.lam, use_whitening=use_whitening
        )

    def loss(self, logprobs, values, old_logprobs, old_values, advantages, returns, mask):
        from trlx_tpu.ops.ppo import ppo_loss

        return ppo_loss(
            logprobs, values, old_logprobs, old_values, advantages, returns, mask,
            cliprange=self.cliprange, cliprange_value=self.cliprange_value,
            vf_coef=self.vf_coef,
        )


@dataclass
@register_method
class GRPOConfig(MethodConfig):
    """GRPO hyperparameters (Group Relative Policy Optimization,
    arXiv:2402.03300): PPO's clipped surrogate with a critic-free
    group-relative advantage — ``group_size`` samples per prompt,
    advantage = per-group reward z-score (ops/grpo.py). No value head,
    no value loss, no critic optimizer state; the KL regularizer sits
    in the LOSS against the frozen reference (``kl_coef``) instead of
    riding the reward. The rollout engine — prompt stream, chunked
    generation, overlap prefetch, decode engine, experience transport,
    rollout fleet — is the shared online core (trainer.base.
    TPUOnlineTrainer): the ``overlap_rollouts`` / ``gen_engine`` /
    ``exp`` / ``fleet`` knobs below carry PPO's exact semantics
    (documented on PPOConfig)."""

    group_size: int = 8
    grpo_epochs: int = 4
    num_rollouts: int = 128
    # samples generated per chunk: chunk_size/group_size prompts are
    # pulled from the stream and each tiled group_size times, so every
    # group's members are consecutive rows of one chunk
    chunk_size: int = 128
    kl_coef: float = 0.001
    cliprange: float = 0.2
    scale_reward: Optional[str] = "ignored"
    ref_mean: Optional[float] = None
    ref_std: Optional[float] = None
    cliprange_reward: float = 10.0
    gen_kwargs: dict = field(default_factory=lambda: dict(max_new_tokens=40))
    gen_experience_kwargs: Optional[dict] = None
    overlap_rollouts: bool = False
    gen_engine: dict = field(default_factory=dict)
    exp: dict = field(default_factory=dict)
    fleet: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.group_size < 2:
            raise ValueError(
                f"grpo.group_size must be >= 2 (got {self.group_size}): a "
                "group of one has no relative baseline"
            )
        if self.chunk_size % self.group_size:
            raise ValueError(
                f"grpo.chunk_size {self.chunk_size} must be divisible by "
                f"group_size {self.group_size} (whole groups per chunk)"
            )
        if self.num_rollouts % self.chunk_size:
            raise ValueError(
                f"grpo.num_rollouts {self.num_rollouts} must be divisible "
                f"by chunk_size {self.chunk_size}: a partial final chunk "
                "would split a group across cycles"
            )

    def loss(self, logprobs, old_logprobs, ref_logprobs, advantages, mask):
        from trlx_tpu.ops.grpo import grpo_loss

        return grpo_loss(
            logprobs, old_logprobs, ref_logprobs, advantages, mask,
            cliprange=self.cliprange, kl_coef=self.kl_coef,
        )


@dataclass
@register_method
class DPOConfig(MethodConfig):
    """DPO hyperparameters (Direct Preference Optimization,
    arXiv:2305.18290): offline sigmoid preference loss over
    policy-vs-frozen-reference logprob margins on (prompt, chosen,
    rejected) pairs. ``beta`` scales the implicit reward;
    ``label_smoothing`` is the conservative-DPO flip probability."""

    beta: float = 0.1
    label_smoothing: float = 0.0
    gen_kwargs: dict = field(default_factory=lambda: dict(max_new_tokens=40))

    def __post_init__(self):
        if self.beta <= 0:
            raise ValueError(f"dpo.beta must be > 0 (got {self.beta})")
        if not 0.0 <= self.label_smoothing < 0.5:
            raise ValueError(
                "dpo.label_smoothing must be in [0, 0.5) (got "
                f"{self.label_smoothing}): past 0.5 the labels invert"
            )

    def loss(self, policy_chosen, policy_rejected, ref_chosen, ref_rejected):
        from trlx_tpu.ops.dpo import dpo_loss

        return dpo_loss(
            policy_chosen, policy_rejected, ref_chosen, ref_rejected,
            beta=self.beta, label_smoothing=self.label_smoothing,
        )


@dataclass
@register_method
class ILQLConfig(MethodConfig):
    """ILQL hyperparameters (field parity with reference modeling_ilql.py:48-93)."""

    tau: float = 0.7
    gamma: float = 0.99
    cql_scale: float = 0.1
    awac_scale: float = 1.0
    alpha: float = 0.001
    beta: float = 0.0
    steps_for_target_q_sync: int = 5
    two_qs: bool = True
    gen_kwargs: dict = field(default_factory=lambda: dict(max_new_tokens=56, top_k=20, beta=1.0))

    def loss(self, outputs, labels):
        from trlx_tpu.ops.ilql import ilql_loss

        logits, (qs, target_qs, vs) = outputs
        return ilql_loss(
            logits, qs, target_qs, vs, labels,
            tau=self.tau, gamma=self.gamma, cql_scale=self.cql_scale,
            awac_scale=self.awac_scale, beta=self.beta, two_qs=self.two_qs,
        )


@dataclass
@register_method
class SFTConfig(MethodConfig):
    """SFT hyperparameters (parity: accelerate_sft_trainer.py:16-26)."""

    gen_kwargs: dict = field(default_factory=lambda: dict(max_new_tokens=40))


@dataclass
@register_method
class RFTConfig(MethodConfig):
    """Rejection-sampling fine-tuning (parity: accelerate_rft_trainer.py:18-44)."""

    gen_kwargs: dict = field(default_factory=lambda: dict(max_new_tokens=40))
    start_percentile: float = 0.7
    end_percentile: float = 0.95
    n_improve_steps: int = 4
    n_generations_per_prompt: int = 32
