"""Typed batch elements as JAX pytrees.

Parity: /root/reference/trlx/data/__init__.py, ppo_types.py, ilql_types.py.
The reference moves lists of per-sample tensors between pipeline and
trainer and needed ad-hoc dataclass<->tensor-list flattening for the NeMo
transport (SURVEY.md §2.3 — broken in the fork). Here every batch type is
a `flax.struct.dataclass`, i.e. a real pytree: jit/pjit/shard_map move
them natively, no bridging code.

All arrays carry static padded shapes (XLA requirement).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.struct
import jax.numpy as jnp


@flax.struct.dataclass
class PromptBatch:
    """A batch of tokenized prompts, left-padded to a fixed length."""

    input_ids: jnp.ndarray  # [batch, prompt_len] int32
    attention_mask: jnp.ndarray  # [batch, prompt_len] int32 (1 = real token)
    # host-side metadata (per-prompt dicts forwarded to reward_fn);
    # pytree-static so it never touches the device
    metadata: Any = flax.struct.field(pytree_node=False, default=None)


@flax.struct.dataclass
class PPORolloutBatch:
    """Batched PPO experience (parity: reference ppo_types.py:6-63).

    The reference stores ragged per-sample tensors and pads at collate
    time (ppo_pipeline.py:14-50); here rollouts are born padded: queries
    left-padded to max_prompt_len, responses right-padded to
    max_new_tokens, so the whole store is one pytree of rectangular
    arrays that lives on device end-to-end.
    """

    query_tensors: jnp.ndarray  # [batch, prompt_len] int32, left-padded
    response_tensors: jnp.ndarray  # [batch, resp_len] int32, right-padded
    logprobs: jnp.ndarray  # [batch, resp_len] f32, per response token
    values: jnp.ndarray  # [batch, resp_len] f32
    rewards: jnp.ndarray  # [batch, resp_len] f32 (KL penalty + terminal score)
    response_mask: jnp.ndarray  # [batch, resp_len] f32 (1 = real response token)
    # experience-transport staleness correction (exp.staleness.mode:
    # clip): per-token clipped importance weight applied to the PPO
    # surrogate (ops/ppo.py is_weight). None outside clip mode — a
    # pytree-empty leaf, so every existing path (store concat, device
    # gathers, fused-scan perms) is untouched when the feature is off.
    is_weight: Optional[jnp.ndarray] = None  # [batch, resp_len] f32
    # gradient-accumulation compensation (the memory doctor's
    # split_microbatch rung, utils/memdoctor.py): GAE advantages +
    # returns PREcomputed over the full minibatch before the microbatch
    # scan splits it, so the whitening statistics match the unsplit
    # step exactly (whitening inside loss() would normalize per
    # microbatch and change numerics). None everywhere else — loss()
    # then computes GAE in-graph as always.
    advantages: Optional[jnp.ndarray] = None  # [batch, resp_len] f32
    returns: Optional[jnp.ndarray] = None  # [batch, resp_len] f32
    # same compensation, for the loss's mask-count normalizer: the
    # full batch's mask total / num_mb, as a constant per-row column
    # (sliced with the microbatch) — each microbatch then normalizes
    # by the same constant and the accumulated mean equals the unsplit
    # sum/N_total exactly, ragged masks included.
    norm_n: Optional[jnp.ndarray] = None  # [batch] f32, constant rows


@flax.struct.dataclass
class GRPORolloutBatch:
    """Batched GRPO experience: PPO's rollout layout minus the value
    column. No ``values``, no ``rewards`` tensor — the sequence-level
    group-relative advantage replaces both, and the KL regularizer is
    computed in-loss from the stored reference logprobs instead of
    being folded into a per-token reward."""

    query_tensors: jnp.ndarray  # [batch, prompt_len] int32, left-padded
    response_tensors: jnp.ndarray  # [batch, resp_len] int32, right-padded
    logprobs: jnp.ndarray  # [batch, resp_len] f32, behavior logprobs
    ref_logprobs: jnp.ndarray  # [batch, resp_len] f32, frozen reference
    advantages: jnp.ndarray  # [batch] f32, per-group reward z-score
    response_mask: jnp.ndarray  # [batch, resp_len] f32 (1 = real token)
    # experience-transport staleness correction (exp.staleness.mode:
    # clip) — same contract as PPORolloutBatch.is_weight
    is_weight: Optional[jnp.ndarray] = None  # [batch, resp_len] f32
    # split-microbatch normalizer compensation — same contract as
    # PPORolloutBatch.norm_n (GRPO has no whitening to compensate; the
    # mask-count normalizer is its only batch-coupled loss term)
    norm_n: Optional[jnp.ndarray] = None  # [batch] f32, constant rows


@flax.struct.dataclass
class DPOBatch:
    """One collated batch of preference pairs: prompt+chosen and
    prompt+rejected rows, right-padded to the dataset's static widths.
    ``*_response_mask`` marks exactly the completion tokens (prompt and
    pad positions contribute nothing to the sequence logprob)."""

    chosen_ids: jnp.ndarray  # [batch, seq] int32
    chosen_attention_mask: jnp.ndarray  # [batch, seq] int32
    chosen_response_mask: jnp.ndarray  # [batch, seq] int32
    rejected_ids: jnp.ndarray  # [batch, seq] int32
    rejected_attention_mask: jnp.ndarray  # [batch, seq] int32
    rejected_response_mask: jnp.ndarray  # [batch, seq] int32


@flax.struct.dataclass
class ILQLBatch:
    """Batched ILQL experience (parity: reference ilql_types.py:7-139)."""

    input_ids: jnp.ndarray  # [batch, seq] int32
    attention_mask: jnp.ndarray  # [batch, seq] int32
    rewards: jnp.ndarray  # [batch, n_actions] f32
    states_ixs: jnp.ndarray  # [batch, n_states] int32
    actions_ixs: jnp.ndarray  # [batch, n_actions] int32
    dones: jnp.ndarray  # [batch, n_states] int32


@flax.struct.dataclass
class ILQLSeq2SeqBatch:
    """ILQL batch for encoder-decoder models."""

    input_ids: jnp.ndarray
    attention_mask: jnp.ndarray
    decoder_input_ids: jnp.ndarray
    rewards: jnp.ndarray
    states_ixs: jnp.ndarray
    actions_ixs: jnp.ndarray
    dones: jnp.ndarray


@flax.struct.dataclass
class SFTBatch:
    """Supervised batch; labels use -100 to mask prompt/pad positions."""

    input_ids: jnp.ndarray  # [batch, seq] int32
    attention_mask: jnp.ndarray  # [batch, seq] int32
    labels: jnp.ndarray  # [batch, seq] int32, -100 = ignored

    # decoder side for seq2seq SFT; None for causal
    decoder_input_ids: Optional[jnp.ndarray] = None
