"""Programmatic default configs.

Parity: /root/reference/trlx/data/default_configs.py:17-148 — same
hyperparameter values so reward curves are comparable; trainer names
point at the TPU trainers and the NeMo OmegaConf loaders are replaced by
mesh presets (parallelism is config here, not a second backend).
"""

from trlx_tpu.data.configs import (
    ModelConfig,
    OptimizerConfig,
    SchedulerConfig,
    TokenizerConfig,
    TrainConfig,
    TRLConfig,
)
from trlx_tpu.data.method_configs import (
    DPOConfig,
    GRPOConfig,
    ILQLConfig,
    PPOConfig,
    RFTConfig,
    SFTConfig,
)


def default_ppo_config() -> TRLConfig:
    return TRLConfig(
        train=TrainConfig(
            seq_length=1024,
            epochs=100,
            total_steps=10000,
            batch_size=32,
            checkpoint_interval=10000,
            eval_interval=100,
            pipeline="PromptPipeline",
            trainer="TPUPPOTrainer",
            tracker=None,
        ),
        model=ModelConfig(model_path="lvwerra/gpt2-imdb", num_layers_unfrozen=2),
        tokenizer=TokenizerConfig(tokenizer_path="gpt2", truncation_side="right"),
        optimizer=OptimizerConfig(
            name="adamw",
            kwargs=dict(lr=3e-5, betas=(0.9, 0.95), eps=1.0e-8, weight_decay=1.0e-6),
        ),
        scheduler=SchedulerConfig(
            name="cosine_annealing", kwargs=dict(T_max=1e12, eta_min=3e-5)
        ),
        method=PPOConfig(
            name="PPOConfig",
            num_rollouts=128,
            chunk_size=128,
            ppo_epochs=4,
            init_kl_coef=0.001,
            target=None,
            horizon=10000,
            gamma=1.0,
            lam=0.95,
            cliprange=0.2,
            cliprange_value=0.2,
            vf_coef=1.0,
            scale_reward="ignored",
            ref_mean=None,
            ref_std=None,
            cliprange_reward=10.0,
            gen_kwargs=dict(max_new_tokens=40, top_k=0, top_p=1.0, do_sample=True),
        ),
    )


def default_grpo_config() -> TRLConfig:
    """GRPO on the PPO sentiments recipe: same model/optimizer/prompt
    stream, critic-free method half. Built standalone rather than by
    evolving the PPO config — ``evolve`` deep-merges the method dict,
    and PPO-only keys (vf_coef, gamma, ...) must not leak into
    GRPOConfig's validation. ``do_sample`` must stay on — a greedy
    group is ``group_size`` identical samples with zero advantage."""
    base = default_ppo_config()
    return TRLConfig(
        train=base.train,
        model=base.model,
        tokenizer=base.tokenizer,
        optimizer=base.optimizer,
        scheduler=base.scheduler,
        method=GRPOConfig(
            name="grpoconfig",
            num_rollouts=128,
            chunk_size=128,
            group_size=8,
            grpo_epochs=4,
            kl_coef=0.001,
            cliprange=0.2,
            scale_reward="ignored",
            cliprange_reward=10.0,
            gen_kwargs=dict(max_new_tokens=40, top_k=0, top_p=1.0, do_sample=True),
        ),
    ).evolve(train=dict(trainer="TPUGRPOTrainer"))


def default_dpo_config() -> TRLConfig:
    """DPO on the SFT recipe: offline preference pairs, frozen
    reference = the initial policy."""
    return default_sft_config().evolve(
        train=dict(trainer="TPUDPOTrainer"),
        optimizer=dict(
            name="adamw",
            kwargs=dict(lr=5.0e-6, betas=(0.9, 0.95), eps=1.0e-8,
                        weight_decay=1.0e-6),
        ),
        scheduler=dict(
            name="cosine_annealing", kwargs=dict(T_max=1e12, eta_min=5.0e-6)
        ),
        method=DPOConfig(
            name="dpoconfig",
            beta=0.1,
            label_smoothing=0.0,
            gen_kwargs=dict(max_new_tokens=40, top_k=0, top_p=1.0, do_sample=True),
        ).to_dict(),
    )


def default_ilql_config() -> TRLConfig:
    return TRLConfig(
        train=TrainConfig(
            seq_length=64,
            batch_size=128,
            epochs=100,
            total_steps=1000,
            checkpoint_interval=1000,
            eval_interval=100,
            pipeline="PromptPipeline",
            trainer="TPUILQLTrainer",
            tracker=None,
        ),
        model=ModelConfig(model_path="gpt2", num_layers_unfrozen=-1),
        tokenizer=TokenizerConfig(tokenizer_path="gpt2", truncation_side="right"),
        optimizer=OptimizerConfig(
            name="adamw",
            kwargs=dict(lr=5.0e-5, betas=(0.9, 0.95), eps=1.0e-8, weight_decay=1.0e-6),
        ),
        scheduler=SchedulerConfig(
            name="cosine_annealing", kwargs=dict(T_max=1e12, eta_min=5.0e-5)
        ),
        method=ILQLConfig(
            name="ilqlconfig",
            tau=0.7,
            gamma=0.99,
            cql_scale=0.1,
            awac_scale=1.0,
            alpha=0.001,
            beta=0.0,
            steps_for_target_q_sync=5,
            two_qs=True,
            gen_kwargs=dict(max_new_tokens=56, top_k=20, beta=1.0, temperature=1.0),
        ),
    )


def default_sft_config() -> TRLConfig:
    return TRLConfig(
        train=TrainConfig(
            seq_length=1024,
            epochs=100,
            total_steps=1000,
            batch_size=8,
            checkpoint_interval=10000,
            eval_interval=100,
            pipeline="PromptPipeline",
            trainer="TPUSFTTrainer",
            tracker=None,
        ),
        model=ModelConfig(model_path="gpt2", num_layers_unfrozen=-1),
        tokenizer=TokenizerConfig(tokenizer_path="gpt2", truncation_side="right"),
        optimizer=OptimizerConfig(
            name="adamw",
            kwargs=dict(lr=1.0e-4, betas=(0.9, 0.95), eps=1.0e-8, weight_decay=1.0e-6),
        ),
        scheduler=SchedulerConfig(
            name="cosine_annealing", kwargs=dict(T_max=1e12, eta_min=1.0e-4)
        ),
        method=SFTConfig(
            name="sftconfig",
            gen_kwargs=dict(max_new_tokens=40, top_k=0, top_p=1.0, do_sample=True),
        ),
    )


def default_rft_config() -> TRLConfig:
    cfg = default_sft_config()
    return cfg.evolve(
        train=dict(trainer="TPURFTTrainer"),
        method=RFTConfig(
            name="rftconfig",
            gen_kwargs=dict(max_new_tokens=40, top_k=0, top_p=1.0, do_sample=True),
        ).to_dict(),
    )


# --- mesh presets replacing the reference's NeMo OmegaConf configs -------
# (megatron_{1.3b,2b,20b,65b}.yaml set TP/PP sizes; here scale is a mesh
# shape choice on the same single trainer.)

def mesh_preset_small() -> dict:
    """Single chip / small pod slice: pure data parallel."""
    return {"dp": -1, "fsdp": 1, "tp": 1, "sp": 1}


def mesh_preset_6b_v3_32() -> dict:
    """GPT-J-6B-class on a v3-32: FSDP over 8, DP over the rest."""
    return {"dp": -1, "fsdp": 8, "tp": 1, "sp": 1}


def mesh_preset_20b_v4() -> dict:
    """NeoX-20B-class on a v4 pod: FSDP x TP."""
    return {"dp": -1, "fsdp": 16, "tp": 4, "sp": 1}
