"""Top-level training configuration.

Parity: /root/reference/trlx/data/configs.py:10-335 — same six sections
(method/model/optimizer/scheduler/tokenizer/train), same field names, same
YAML / dict round-trip, `evolve()` deep-merge and dotted-path `update()`
semantics — reimplemented generically over a section table.

TPU-specific additions live in TrainConfig (mesh shape / sharding axes):
the reference splits parallelism across two backends (Accelerate vs NeMo,
SURVEY.md §2.4/2.6); here parallelism is config, not code.
"""

from __future__ import annotations

import dataclasses
import json
from copy import deepcopy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import yaml

from trlx_tpu.data.method_configs import MethodConfig, get_method


def _deep_merge(base: Dict, update: Dict) -> Dict:
    """Return a new dict: `base` recursively overridden by `update`."""
    out = deepcopy(base)
    for key, val in update.items():
        if isinstance(val, dict) and isinstance(out.get(key), dict):
            out[key] = _deep_merge(out[key], val)
        else:
            out[key] = val
    return out


def _unflatten(config: Dict[str, Any]) -> Dict[str, Any]:
    """Expand dotted keys: {"a.b.c": 1} -> {"a": {"b": {"c": 1}}}."""
    nested: Dict[str, Any] = {}
    for name, value in config.items():
        node = nested
        *path, leaf = name.split(".")
        for part in path:
            node = node.setdefault(part, {})
        if isinstance(value, dict) and not path:
            node[leaf] = _deep_merge(node.get(leaf, {}), value)
        else:
            node[leaf] = value
    return nested


class _Section:
    """Shared from_dict/to_dict for config sections with unknown-key checks."""

    @classmethod
    def from_dict(cls, config: Dict[str, Any]):
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(config) - known
        if unknown:
            raise ValueError(f"{cls.__name__}: unknown keys {sorted(unknown)}")
        return cls(**config)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclass
class ModelConfig(_Section):
    """Model selection (parity: reference configs.py:37-72).

    model_path: HF-layout local directory (or name; hub access is optional),
    model_arch_type: "causal" | "seq2seq",
    num_layers_unfrozen: -1 trains all layers; k>0 trains only the top k and
      enables the in-process frozen reference branch (hydra) for PPO.
    """

    model_path: str
    model_arch_type: str = "causal"
    num_layers_unfrozen: int = -1
    peft_config: Any = None
    model_extra_configs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class TokenizerConfig(_Section):
    """Tokenizer selection (parity: reference configs.py:75-97)."""

    tokenizer_path: str
    padding_side: str = "left"
    truncation_side: str = "right"
    tokenizer_extra_configs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class OptimizerConfig(_Section):
    """Optimizer name + kwargs, resolved via trlx_tpu.utils registry."""

    name: str
    kwargs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class SchedulerConfig(_Section):
    """LR schedule name + kwargs, resolved via trlx_tpu.utils registry."""

    name: str
    kwargs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class TrainConfig(_Section):
    """Training-loop settings (parity: reference configs.py:140-236) plus
    TPU mesh fields (`mesh`, `sharding`) replacing the reference's
    accelerate/deepspeed YAML + NeMo OmegaConf split."""

    total_steps: int
    seq_length: int
    epochs: int
    batch_size: int

    checkpoint_interval: int
    eval_interval: int

    pipeline: str
    trainer: str
    trainer_kwargs: Dict[str, Any] = field(default_factory=dict)

    project_name: str = "trlx_tpu"
    run_name: Optional[str] = None
    entity_name: Optional[str] = None
    group_name: Optional[str] = None

    checkpoint_dir: str = "ckpts"
    rollout_logging_dir: Optional[str] = None
    save_best: bool = True
    save_optimizer: bool = True
    # A checkpoint directory to restore full training state from, or
    # "auto": discover the newest COMMITted checkpoint_* under
    # checkpoint_dir and resume it (fresh start, with a logged warning,
    # when none exists). Resume continues from the saved iter_count /
    # best_reward / PRNG key / data cursor — it does not replay from 0.
    resume_from_checkpoint: Optional[str] = None
    # Retention: keep only the newest N committed checkpoint_* dirs
    # (best_checkpoint always survives). None keeps everything.
    keep_last_n: Optional[int] = None

    tracker: Optional[str] = "tensorboard"
    logging_dir: Optional[str] = None
    tags: List[str] = field(default_factory=list)

    seed: int = 1000

    minibatch_size: Optional[int] = None

    # --- TPU-native additions -------------------------------------------
    # Mesh axis sizes; any axis set to -1 absorbs the remaining devices.
    # dp: data parallel, fsdp: param/opt-state sharded data parallel
    # (ZeRO-3 parity), tp: tensor parallel (Megatron parity), sp: sequence
    # (context) parallel for long sequences (ring attention), pp: pipeline
    # parallel (GPipe microbatching over the stacked layer axis; mutually
    # exclusive with sp).
    mesh: Dict[str, int] = field(default_factory=lambda: {"dp": -1, "fsdp": 1, "tp": 1, "sp": 1})
    # Precision of params/compute; optimizer state stays fp32.
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    # Rematerialization policy for transformer blocks (NeMo activation-
    # checkpointing granularity parity — megatron_20b.yaml:76-80):
    # "none" | "full" (= "save_nothing": keep layer boundaries only) |
    # "dots_saveable" (keep matmul outputs, recompute elementwise —
    # NeMo "selective") | "dots_with_no_batch_dims" (keep weight-
    # stationary matmul results only) | "offload" (same, saved to
    # pinned host memory) | "save_attn" (full recompute except the
    # pallas attention kernel's named residuals — the long-context
    # winner, docs/benchmarks.md). See trlx_tpu/ops/remat.py.
    remat_policy: str = "none"
    # When > 0, trainer losses compute per-token logprobs / cross-entropy
    # from hidden states in this many sequence chunks under
    # jax.checkpoint (ops.common.chunked_logprobs) instead of
    # materializing the full [batch, seq, vocab] fp32 logits — at
    # b8/seq2048/vocab50257 that single tensor is 3.3 GB per
    # materialization, the difference between billion-parameter training
    # fitting one 16 GB chip or not. 0 = off. The at-scale recipe
    # (docs/benchmarks.md) uses 8.
    logit_chunks: int = 0
    # When set (e.g. "bfloat16"), losses are differentiated through a
    # grads_dtype view of the params, so the gradient tree rides in that
    # dtype (half the HBM of fp32 grads at 1.3B: 2.6 GB vs 5.3 GB).
    # Params and optimizer masters stay `param_dtype`; with
    # minibatch accumulation the running sum stays fp32.
    grads_dtype: Optional[str] = None
    # When set, a jax.profiler trace of train steps [profile_start,
    # profile_stop) is written here (the reference exposes Nsight knobs in
    # its NeMo configs — megatron_20b.yaml:126-131; this is the XLA
    # equivalent, viewable in TensorBoard / Perfetto).
    profile_dir: Optional[str] = None
    profile_start: int = 2
    profile_stop: int = 5
    # The train step fuses forward+backward+update under one jit, so only
    # `time/step` can be reported per-step. Enabling this measures a
    # forward-only pass once (shapes are static, so its cost is constant)
    # and emits `time/forward` = that measurement and `time/backward` =
    # step - forward, matching the reference's metric keys.
    timing_split: bool = False
    # --- fault tolerance ------------------------------------------------
    # Non-finite (NaN/inf) loss or grads: commit the PRE-update
    # params/opt_state instead of the poisoned update (a traced select
    # inside the jitted step — the buffers are donated, so the host
    # could not roll back). With the fused 8-bit optimizer the guard
    # zeroes the gradients before the apply instead, so a poisoned step
    # degrades to a weight-decay-only update (docs/api.md).
    skip_nan_updates: bool = True
    # Abort the run after this many CONSECUTIVE skipped (non-finite)
    # steps: persistent NaN means diverged state, not a transient.
    max_bad_steps: int = 3
    # Retry budget (re-tries after the first attempt) for the two
    # external calls in the loop — tracker.log and the reward function —
    # with exponential backoff from retry_base_delay (doubling, capped,
    # jittered). A tracker that stays down degrades to a logged error;
    # a reward function that stays down fails the run.
    external_retries: int = 3
    retry_base_delay: float = 0.5
    # Run ALL inner-epoch optimizer steps as one jitted lax.scan over
    # minibatch permutations instead of one dispatch per minibatch
    # (trainers that hold the epoch's data as a rectangular batch — PPO's
    # rollout store — support this; others fall back to the per-step
    # loop). Removes per-step dispatch latency and host syncs; per-step
    # metric granularity collapses to per-block means. The scanned path
    # draws its shuffles from the same seed stream as the looped
    # dataloaders, so it is numerically equivalent step-for-step
    # (tests/test_scanned_epochs.py); checkpoint/eval cadence quantizes
    # to block boundaries when the intervals don't divide the block.
    # Default ON since the dispatch-free-cycle change; set False for
    # exact per-step cadence/metrics.
    fused_inner_loop: bool = True
    # Defer fused-block metrics behind an async device->host copy and
    # consume them one cycle later (next block start / learn() exit):
    # the host never blocks on the device between cycle boundaries, so
    # per-block `jax.block_until_ready`-style fetches (a full host
    # round-trip each on a remote-tunneled chip) disappear from the
    # steady-state loop. Checkpoint/eval boundary blocks still flush
    # synchronously (those operations block on the device anyway), and
    # the NaN-abort guard then fires at most one cycle late. False
    # restores the immediate per-block fetch.
    async_metrics: bool = True
    # --- run guardrails (divergence watchdog) ---------------------------
    # Parsed by utils/guardrails.GuardrailConfig (enabled/window/
    # loss_spike_sigma/kl_factor/reward_sigma/grad_norm_max/
    # cycle_time_factor/consistency_every/consistency_atol/ladder/
    # lr_cut_factor/cooldown_cycles/max_rollbacks/recover_after).
    # consistency_every > 0 arms the cross-host consistency watchdog:
    # a cheap param/opt-state fingerprint is allgather-compared every N
    # cycles (multihost.consensus) and a disagreeing host trips the
    # ladder. Default {} = disabled: identical
    # behavior to pre-guardrails builds. When enabled, health trips walk
    # the escalation ladder (log -> requeue -> lr_cut -> rollback ->
    # abort), checkpoint commits are gated on health, and auto-rollback
    # restores the last good checkpoint. See docs/robustness.md.
    guardrails: Dict[str, Any] = field(default_factory=dict)
    # --- resilient external I/O -----------------------------------------
    # Parsed by utils/resilient.ResilientIOConfig (reward_timeout/
    # retries/base_delay/max_delay/jitter/breaker_threshold/
    # breaker_reset_s/fallback_reward). Default {} keeps PR 1 semantics:
    # plain retry+backoff, reward failures propagate. Setting
    # fallback_reward ("hold_mean" or a number) arms the circuit
    # breaker and degrades a dead reward service to the fallback instead
    # of failing the run; reward_timeout bounds each attempt.
    resilient_io: Dict[str, Any] = field(default_factory=dict)
    # --- elastic recovery (topology-change resume + ckpt integrity) ----
    # Parsed by utils/checkpointing.ElasticConfig (integrity/
    # verify_integrity/allow_topology_change). Defaults (all true):
    # every checkpoint commit includes a per-file sha256 manifest and a
    # topology manifest; trainer.load() verifies the hashes first and
    # QUARANTINES a mismatching checkpoint (renamed *.corrupt, never
    # deleted) — auto-resume and guardrail auto-rollback then fall back
    # to the previous committed step; and a checkpoint saved under a
    # different mesh/host-count restores onto the CURRENT mesh
    # (params/opt-state resharded, PPO prompt stream re-split). See
    # docs/robustness.md "Elastic recovery".
    elastic: Dict[str, Any] = field(default_factory=dict)
    # --- hang doctor (watchdog: phase heartbeats + stall detection) -----
    # Parsed by utils/watchdog.WatchdogConfig (enabled/default_deadline_s/
    # deadline_s (per-phase: rollout/reward/fused_block/train_step/
    # checkpoint/eval/experience)/scale_factor/min_samples/window/
    # poll_interval_s/timeline/idle_deadline_s/dump_stacks/
    # emergency_snapshot/barrier_timeout_s). Default {} = disabled (no
    # monitor thread, beats are free). When enabled, trainers heartbeat
    # at phase boundaries and a monitor thread trips when a phase goes
    # silent past its deadline (deadlines are FLOORS, auto-raised to
    # scale_factor * the observed rolling median duration so slow-but-
    # healthy CPU runs don't false-trip). On trip: all-thread stack dump
    # + phase timeline -> emergency snapshot from the host-RAM shadow of
    # the last health-gated state -> abort with the "stalled" exit class
    # (watchdog.EXIT_STALLED = 87), distinguishable from a crash. See
    # docs/robustness.md "Hang doctor".
    watchdog: Dict[str, Any] = field(default_factory=dict)
    # --- memory doctor (HBM admission control + OOM recovery ladder) ----
    # Parsed by utils/memdoctor.MemoryConfig (enabled/preflight/
    # hbm_bytes/headroom/high_watermark/watermark_window/
    # sample_interval_s/ladder/pool_shrink_factor/max_pool_shrinks/
    # max_splits/remat_escalation/accept_undegrade). Default {} =
    # disabled: no preflight, no watermark sampler, RESOURCE_EXHAUSTED
    # propagates raw. When enabled: learn() first builds an analytic
    # per-phase HBM plan (params/opt/grads/activations; decode-engine
    # page pools + draft model) and REJECTS an over-budget config with
    # an itemized report before any compile; a host-side sampler feeds
    # the `memory` guardrail signal when bytes-in-use crosses the high
    # watermark; and an OOM walks the degradation ladder — shrink the
    # gen-engine page pool -> split the train microbatch (golden-equal
    # grad accumulation) -> escalate remat -> rollback to the last
    # health-gated checkpoint with the degradation PERSISTED in
    # state.json -> itemized abort. See docs/robustness.md "Memory
    # doctor".
    memory: Dict[str, Any] = field(default_factory=dict)
    # --- flight recorder / run telemetry (observability) ----------------
    # Parsed by obs.ObsConfig (enabled/dir/rotate_bytes/keep_files/
    # telemetry_window/events_tail/profile.{start_cycle,stop_cycle,
    # on_trip,dir,force}). DEFAULT ON (unlike the other subsystems —
    # the point is that every run self-documents): a span tracer rides
    # the hang doctor's existing beat sites to produce a per-cycle
    # phase wall-time breakdown (phase sum == cycle wall by
    # construction); guardrail trips, chaos injections, memdoctor
    # watermark/OOM-ladder events, fleet degradations and supervisor
    # restarts all land in ONE size-rotated JSONL flight-recorder
    # stream under <checkpoint_dir>/flight/, correlated by
    # run_id/cycle/policy_version; and a provenance-stamped
    # telemetry.json with the bench-comparable headline numbers
    # (samples/s, mask-weighted tokens/s, phase breakdown, engine
    # ledger, analytic MFU estimate) is committed alongside every
    # checkpoint. train.obs.profile.* arms an on-demand jax.profiler
    # window (cycles N..M, or one-shot on a perf/memory guardrail
    # trip). Host-side only, no device syncs; {enabled: false}
    # restores pre-obs behavior. Render with scripts/flight_report.py;
    # runbook: docs/observability.md.
    obs: Dict[str, Any] = field(default_factory=dict)
    # --- live-traffic serving tier --------------------------------------
    # Parsed by serve.config.ServeConfig (enabled/max_batch/slots/
    # page_size/pool_pages/max_prompt_len/max_new_tokens/
    # default_max_tokens/default_deadline_s/kv_quant/
    # max_batches_per_tick/starvation_report_after/prefix_cache/
    # sessions/session_deadline_s/max_cache_entries/transport/seed).
    # Default {} = disabled. When enabled, learn() hosts a serving
    # frontend on the SAME continuous-batching decode engine that
    # produces training rollouts, on the live policy params: external
    # requests (prompt, max_tokens, sampling seed-by-request-id,
    # deadline) are admitted at the lane-refill decision points with
    # SLO scheduling (EDF; serving outranks training refills under a
    # bounded per-tick allowance; deadline-expired requests are evicted
    # with their pages reclaimed), a refcounted prefix/session KV cache
    # shares page-aligned system prompts across requests and pins
    # multi-turn sessions, and requests arrive over a pluggable
    # transport (shared_fs under <checkpoint_dir>/serve, or a tcp hub).
    # The training loss stream stays bit-equal to a no-serving run by
    # construction. See docs/serving.md.
    serve: Dict[str, Any] = field(default_factory=dict)
    # --- chaos injection (tests/CI only) --------------------------------
    # Parsed by utils/chaos.ChaosMonkey: {"seed": int, "faults": [
    # {"fault": "nan_loss"|"sigterm"|"nan_reward"|"reward_timeout"|
    # "reward_error"|"ckpt_fail"|"ckpt_corrupt"|"host_divergence"|
    # "stall_rollout"|"stall_reward"|"stall_collective"|
    # "worker_death_mid_lease"|"duplicate_delivery"|"stale_flood"|
    # "queue_wedge"|"fleet_worker_death"|"fleet_partition"|
    # "broadcast_corrupt"|"oom_fused_block"|"oom_prefill"|"hbm_creep"|
    # "serve_request_timeout"|"serve_lane_starvation"|
    # "serve_transport_drop",
    # "at": k | "every": n | "p": x,
    # "span": m}], "reward_delay": s, "stall_delay": s}. None/{}
    # disables. Deterministic given the seed — see docs/robustness.md
    # for the schedule format (the stall_* sites sleep stall_delay
    # seconds to prove the hang doctor end to end; the oom_* sites
    # raise simulated RESOURCE_EXHAUSTED for the memory doctor's
    # ladder, hbm_creep saturates its watermark sampler).
    chaos: Optional[Dict[str, Any]] = None


_SECTIONS: Tuple[Tuple[str, type], ...] = (
    ("model", ModelConfig),
    ("tokenizer", TokenizerConfig),
    ("optimizer", OptimizerConfig),
    ("scheduler", SchedulerConfig),
    ("train", TrainConfig),
)


@dataclass
class TRLConfig:
    """Top-level config (parity: reference configs.py:239-335)."""

    method: MethodConfig
    model: ModelConfig
    optimizer: OptimizerConfig
    scheduler: SchedulerConfig
    tokenizer: TokenizerConfig
    train: TrainConfig

    @classmethod
    def load_yaml(cls, yml_fp: str) -> "TRLConfig":
        with open(yml_fp) as f:
            return cls.from_dict(yaml.safe_load(f))

    @classmethod
    def from_dict(cls, config: Dict[str, Any]) -> "TRLConfig":
        sections = {name: sec.from_dict(config[name]) for name, sec in _SECTIONS}
        method_cls = get_method(config["method"]["name"])
        return cls(method=method_cls.from_dict(config["method"]), **sections)

    def to_dict(self) -> Dict[str, Any]:
        data = {name: getattr(self, name).to_dict() for name, _ in _SECTIONS}
        data["method"] = self.method.to_dict()
        return data

    def evolve(self, **kwargs) -> "TRLConfig":
        """Deep-merge keyword overrides, returning a new config.

        >>> cfg.evolve(method=dict(gamma=0.99), train=dict(seed=7))
        """
        return TRLConfig.from_dict(_deep_merge(self.to_dict(), kwargs))

    @classmethod
    def update(cls, baseconfig, config: Dict[str, Any]) -> "TRLConfig":
        """Apply dotted-path overrides ("train.seed": 1) with validation that
        every override path exists in the base (sweep-tool contract,
        reference configs.py:303-329)."""
        if not isinstance(baseconfig, dict):
            baseconfig = baseconfig.to_dict()
        overrides = _unflatten(config)

        def _check(base, upd, path=""):
            for k, v in upd.items():
                if k not in base:
                    raise ValueError(f"parameter {path}{k} is not present in the config")
                if isinstance(v, dict) and isinstance(base[k], dict):
                    _check(base[k], v, f"{path}{k}.")

        _check(baseconfig, overrides)
        return cls.from_dict(_deep_merge(baseconfig, overrides))

    def __str__(self) -> str:
        return json.dumps(self.to_dict(), indent=4)
