"""`trlx_tpu.train` — the single user entry point.

Parity: /root/reference/trlx/trlx.py:15-143 — same signature and the same
argument-driven algorithm selection: `reward_fn` -> online PPO,
`rewards`/`dataset` -> offline ILQL, otherwise SFT.

Beyond the reference's four algorithms the registry also carries the
critic-free preference-RL pair: `train.trainer="TPUGRPOTrainer"` runs
GRPO through the online branch (same `reward_fn` + `prompts` contract
as PPO, riding the shared experience core), and
`train.trainer="TPUDPOTrainer"` runs DPO through the offline branch
with `samples` as (prompt, chosen, rejected) preference triples and no
`rewards`.
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple, Union

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.data.default_configs import (
    default_ilql_config,
    default_ppo_config,
    default_sft_config,
)
from trlx_tpu.utils import logging, set_seed
from trlx_tpu.utils.loading import get_pipeline, get_trainer

logger = logging.get_logger(__name__)


def train(
    model_path: Optional[str] = None,
    reward_fn: Optional[Callable[[List[str], List[str], List[str]], List[float]]] = None,
    dataset: Optional[Iterable[Tuple[str, float]]] = None,
    samples: Optional[List[str]] = None,
    rewards: Optional[List[float]] = None,
    prompts: Optional[Union[List[str], List[Dict[str, Any]]]] = None,
    eval_prompts: Optional[Union[List[str], List[Dict[str, Any]]]] = None,
    metric_fn: Optional[Callable[[List[str], List[str], List[str]], Dict[str, List[float]]]] = None,
    config: Optional[TRLConfig] = None,
    stop_sequences: Optional[List[str]] = None,
):
    """Run online RL (PPO), offline RL (ILQL) or supervised fine-tuning,
    selected by which arguments are provided.

    reward_fn(samples, prompts, outputs, **metadata) -> list of scalar
    rewards drives online training; (samples, rewards) drive offline
    training; samples alone drive SFT.
    """
    if config is None:
        warnings.warn(
            "Passing the `config` argument implicitly is depreciated, use or"
            "adapt some from `trlx_tpu/data/default_configs.py` instead"
        )
        if reward_fn:
            config = default_ppo_config()
        elif rewards:
            config = default_ilql_config()
        else:
            config = default_sft_config()

    set_seed(config.train.seed)

    if dataset is not None:
        warnings.warn("the `dataset` argument is being depreciated, split it into `samples` and `rewards` instead")
        samples, rewards = dataset

    if model_path:
        config.model.model_path = model_path

    trainer_cls = get_trainer(config.train.trainer)
    trainer = trainer_cls(
        config=config,
        reward_fn=reward_fn,
        metric_fn=metric_fn,
        stop_sequences=stop_sequences or [],
        **config.train.trainer_kwargs,
    )

    batch_size = config.train.batch_size
    max_prompt_length = config.train.seq_length - config.method.gen_kwargs.get(
        "max_new_tokens", 0
    )
    if max_prompt_length <= 0:
        raise ValueError(
            f"train.seq_length ({config.train.seq_length}) must exceed "
            f"gen_kwargs['max_new_tokens'] "
            f"({config.method.gen_kwargs.get('max_new_tokens', 0)}): prompts "
            "would be truncated to zero tokens"
        )

    # --- online ----------------------------------------------------------
    if reward_fn:
        if prompts is None:
            raise ValueError("`prompts` are required for online training")
        if eval_prompts is None:
            eval_prompts = prompts[:batch_size]

        pipeline = get_pipeline(config.train.pipeline)(
            prompts, max_prompt_length, trainer.tokenizer
        )
        trainer.add_prompt_pipeline(pipeline)

    # --- offline RL ------------------------------------------------------
    elif rewards is not None:
        if samples is None:
            raise ValueError("`samples` are required alongside `rewards`")
        if eval_prompts is None:
            eval_prompts = [trainer.tokenizer.bos_token] * batch_size
        trainer.make_experience(samples, rewards, config.train.seq_length)

    # --- supervised / offline preference pairs ---------------------------
    else:
        if samples is None:
            raise ValueError("Either `samples`, `rewards` or `reward_fn` must be given")
        if eval_prompts is None:
            eval_prompts = [trainer.tokenizer.bos_token] * batch_size
        # SFT takes strings or (prompt, output) dialogues; DPO takes
        # (prompt, chosen, rejected) triples — the trainer validates
        trainer.make_experience(samples, None, config.train.seq_length)

    eval_pipeline = get_pipeline(config.train.pipeline)(
        eval_prompts, max_prompt_length, trainer.tokenizer
    )
    trainer.add_eval_pipeline(eval_pipeline)

    import os

    resume = config.train.resume_from_checkpoint
    env_resume = os.environ.get("TRLX_TPU_RESUME_FROM")
    if env_resume:
        # the run supervisor's relaunch channel (scripts/supervise.py):
        # after a stalled exit (class 87) it points the next attempt at
        # the hang doctor's emergency snapshot — which auto-discovery
        # deliberately never picks up — without editing the config the
        # operator wrote
        logger.warning(
            "TRLX_TPU_RESUME_FROM=%s overrides "
            "train.resume_from_checkpoint=%r for this launch",
            env_resume, resume,
        )
        resume = env_resume
    if resume == "auto":
        from trlx_tpu.parallel import multihost as mh
        from trlx_tpu.utils.checkpointing import CheckpointCorruptError

        # discover the newest COMMITted checkpoint under checkpoint_dir;
        # torn directories (preemption mid-save) and deploy-only ones
        # (save_optimizer=false) are skipped, and "nothing yet" is a
        # fresh start — the standard relaunch loop on preemptible pods
        # points every attempt at the same command line. A checkpoint
        # that fails integrity verification is QUARANTINED by load()
        # (renamed *.corrupt) and discovery falls back to the previous
        # committed step instead of crashing every relaunch on poison.
        while True:
            resume = trainer.ckpt_manager.latest_resumable()
            if mh.is_multihost():
                # stale shared-filesystem metadata can show different
                # hosts different listings; every process must load the
                # SAME checkpoint (or none), so process 0's discovery wins
                resume = mh.allgather_object(resume)[0]
            if resume is None:
                logger.warning(
                    "resume_from_checkpoint='auto': no committed checkpoint "
                    "under %s — starting fresh", config.train.checkpoint_dir,
                )
                break
            logger.info("Resuming from checkpoint %s", resume)
            try:
                trainer.load(resume)
                break
            except CheckpointCorruptError as e:
                logger.error(
                    "auto-resume: %s — falling back to the previous "
                    "committed checkpoint", e,
                )
    elif resume:
        # an explicitly named checkpoint: a corrupt one is an error the
        # user must see (no silent fallback to a different step), and
        # the pinned path is NOT renamed — a transient storage mismatch
        # must not permanently break the path the user configured
        logger.info("Resuming from checkpoint %s", resume)
        trainer.load(resume, quarantine_corrupt=False)

    trainer.learn()
    return trainer
