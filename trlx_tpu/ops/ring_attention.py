"""Ring attention: sequence/context parallelism over the `sp` mesh axis.

The reference has NO context parallelism — its long-context story is
Megatron sequence parallelism (activation sharding during norms) plus
activation checkpointing, capped at seq_length 2048
(SURVEY.md §2.7 row CP; configs/nemo_configs/megatron_20b.yaml:57). This
module is the TPU-native upgrade the survey calls for: each `sp` shard
holds one block of the sequence; K/V blocks rotate around the ring via
`ppermute` (ICI neighbor exchange) while every shard accumulates its
queries' attention with an online-softmax (flash-style m/l running
state). Peak memory per chip is O(T/sp · T/sp) instead of O(T²), and the
K/V transfer overlaps with the block matmuls.

`ring_attention` is the shard_map-aware primitive; `ring_attention_sharded`
wraps it for a [B, T, H, D] tensor sharded ('sp' on T) over a mesh.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attention(q, k, v, bias, m_prev, l_prev, o_prev):
    """One flash-attention accumulation step.

    q: [B, Tq, H, D], k/v: [B, Tk, H, D], bias: [B, 1, Tq, Tk] additive.
    Carries the running max (m), normalizer (l) and un-normalized output.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    s = s + bias
    # the softmax max-shift cancels analytically (d out / d m == 0), so the
    # running max is detached: without this, cotangents route through the
    # max/isfinite/exp chain and turn into NaN via inf*0 on fully-masked
    # (padding) rows
    m_cur = jax.lax.stop_gradient(jnp.max(s, axis=-1))  # [B, H, Tq]
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked rows (all -inf): exp(-inf - -inf) -> keep finite
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])  # [B, H, Tq, Tk]
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
    l_new = l_prev * alpha + p.sum(axis=-1)
    o_new = o_prev * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(p.dtype), preferred_element_type=jnp.float32
    )
    return m_new, l_new, o_new


def ring_attention(
    q: jnp.ndarray,  # [B, T_local, H, D] — this shard's queries
    k: jnp.ndarray,  # [B, T_local, H, D]
    v: jnp.ndarray,
    segment_mask: Optional[jnp.ndarray] = None,  # [B, T_local] 1 = real
    axis_name: str = "sp",
    causal: bool = True,
) -> jnp.ndarray:
    """Blockwise attention with K/V rotating around the `axis_name` ring.

    Must run inside shard_map/pmap with `axis_name` bound. Causality is
    enforced across blocks by comparing global positions (shard i holds
    positions [i*T_local, (i+1)*T_local))."""
    sp = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, T, H, D = q.shape
    q32 = q.astype(jnp.float32)

    q_pos = my * T + jnp.arange(T)  # global positions of local queries

    # derive the accumulators from q so they carry shard_map's
    # device-varying type (fresh constants would be typed as replicated
    # and fail the scan carry check); stop_gradient because they are
    # semantically constants — without it the backward pass routes
    # cotangents through `m0`'s -inf (inf * 0.0 = NaN in the q grads)
    qT = jax.lax.stop_gradient(q32.transpose(0, 2, 1, 3))  # [B, H, T, D]
    m0 = qT[..., 0] * 0.0 - jnp.inf
    l0 = qT[..., 0] * 0.0
    o0 = qT * 0.0
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def body(carry, step):
        k_blk, v_blk, mask_blk, m, l, o = carry
        src = (my - step) % sp  # which shard's block we now hold
        k_pos = src * T + jnp.arange(T)
        bias = jnp.zeros((B, 1, T, T), jnp.float32)
        if causal:
            bias = bias + jnp.where(
                q_pos[:, None] >= k_pos[None, :], 0.0, NEG_INF
            )[None, None]
        if mask_blk is not None:
            bias = bias + jnp.where(mask_blk[:, None, None, :] > 0, 0.0, NEG_INF)
        m, l, o = _block_attention(q32, k_blk, v_blk, bias, m, l, o)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        if mask_blk is not None:
            mask_blk = jax.lax.ppermute(mask_blk, axis_name, perm)
        return (k_blk, v_blk, mask_blk, m, l, o), None

    carry = (k.astype(jnp.float32), v.astype(jnp.float32), segment_mask, m0, l0, o0)
    (k_f, v_f, _, m, l, o), _ = jax.lax.scan(body, carry, jnp.arange(sp))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, T, H, D]


def ring_attention_sharded(
    q: jnp.ndarray,  # [B, T, H, D] (global)
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    segment_mask: Optional[jnp.ndarray] = None,
    causal: bool = True,
) -> jnp.ndarray:
    """shard_map wrapper: sequence dim sharded over 'sp', batch over
    (dp, fsdp), heads over 'tp'."""
    from jax.experimental.shard_map import shard_map

    spec_qkv = P(("dp", "fsdp"), "sp", "tp", None)
    spec_mask = P(("dp", "fsdp"), "sp")

    fn = partial(ring_attention, axis_name="sp", causal=causal)
    if segment_mask is None:
        sharded = shard_map(
            lambda q_, k_, v_: fn(q_, k_, v_),
            mesh=mesh, in_specs=(spec_qkv,) * 3, out_specs=spec_qkv,
        )
        return sharded(q, k, v)
    sharded = shard_map(
        lambda q_, k_, v_, m_: fn(q_, k_, v_, segment_mask=m_),
        mesh=mesh, in_specs=(spec_qkv,) * 3 + (spec_mask,), out_specs=spec_qkv,
    )
    return sharded(q, k, v, segment_mask)
