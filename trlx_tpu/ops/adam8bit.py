"""Blockwise 8-bit AdamW: the bitsandbytes replacement, as a first-party
optax transformation.

Parity: the reference offers `adamw_8bit_bnb` through bitsandbytes'
CUDA kernels (/root/reference/trlx/utils/__init__.py:104-123,
accelerate_base_trainer.py:183-191). The TPU-native shape is the same
math with the moment states held in int8 + per-block fp32 absmax scales
(block 256, bnb's default): m is symmetric int8, v (non-negative) uses
the positive half. Dequantize -> fused adam update -> requantize runs
inside the jitted train step; XLA fuses the (de)quantization into the
update elementwise pass, so the win is the 4x smaller optimizer state in
HBM (the dominant term beyond params for fsdp-sharded training), not
kernel time.
"""

from __future__ import annotations

from typing import NamedTuple

import flax
import jax
import jax.numpy as jnp
import optax

BLOCK = 256


@flax.struct.dataclass
class Q8:
    q: jnp.ndarray  # int8 payload, flattened + padded to BLOCK
    scale: jnp.ndarray  # f32 per-block absmax
    shape: tuple = flax.struct.field(pytree_node=False)  # original (static)


def _quantize(x: jnp.ndarray) -> Q8:
    """Blockwise companded int8: q = sign * 127 * sqrt(|x| / absmax).

    The sqrt companding matches bitsandbytes' non-linear dynamic map in
    spirit: Adam's second moment spans orders of magnitude within one
    block, and a LINEAR absmax code wipes out the small entries, which
    visibly corrupts the update direction (sqrt(vhat) sits in the
    denominator)."""
    shape = x.shape
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-flat.size) % BLOCK
    flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(-1, BLOCK)
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    norm = jnp.abs(blocks) / jnp.maximum(scale, 1e-30)
    q = jnp.round(jnp.sign(blocks) * jnp.sqrt(norm) * 127.0)
    return Q8(q.astype(jnp.int8), scale[:, 0], shape)


def _dequantize(s: Q8) -> jnp.ndarray:
    u = s.q.astype(jnp.float32) / 127.0
    blocks = jnp.sign(u) * u * u * s.scale[:, None]
    flat = blocks.reshape(-1)
    n = 1
    for d in s.shape:
        n *= d
    return flat[:n].reshape(s.shape)


class Adam8bitState(NamedTuple):
    count: jnp.ndarray
    m: optax.Params  # tree of Q8
    v: optax.Params  # tree of Q8


def scale_by_adam_8bit(b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8):
    """optax transformation holding both Adam moments in blockwise int8."""

    def init(params):
        zeros = jax.tree_util.tree_map(
            lambda p: _quantize(jnp.zeros(p.shape, jnp.float32)), params
        )
        return Adam8bitState(count=jnp.zeros([], jnp.int32), m=zeros, v=zeros)

    def update(updates, state, params=None):
        count = state.count + 1

        def one(g, mq, vq):
            g = g.astype(jnp.float32)
            m = b1 * _dequantize(mq) + (1 - b1) * g
            v = b2 * _dequantize(vq) + (1 - b2) * g * g
            mhat = m / (1 - b1 ** count.astype(jnp.float32))
            vhat = v / (1 - b2 ** count.astype(jnp.float32))
            step = mhat / (jnp.sqrt(vhat) + eps)
            return step, _quantize(m), _quantize(v)

        flat_u, tdef = jax.tree_util.tree_flatten(updates)
        flat_m = tdef.flatten_up_to(state.m)
        flat_v = tdef.flatten_up_to(state.v)
        out = [one(g, m, v) for g, m, v in zip(flat_u, flat_m, flat_v)]
        steps = tdef.unflatten([o[0] for o in out])
        new_m = tdef.unflatten([o[1] for o in out])
        new_v = tdef.unflatten([o[2] for o in out])
        return steps, Adam8bitState(count=count, m=new_m, v=new_v)

    return optax.GradientTransformation(init, update)


def adamw_8bit(
    learning_rate, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
    weight_decay: float = 0.0,
):
    """AdamW with int8 moment states (drop-in for optax.adamw)."""
    chain = [scale_by_adam_8bit(b1=b1, b2=b2, eps=eps)]
    if weight_decay:
        chain.append(optax.add_decayed_weights(weight_decay))
    chain.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*chain)
