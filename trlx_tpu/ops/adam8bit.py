"""Blockwise 8-bit AdamW: the bitsandbytes replacement, as a first-party
optax transformation.

Parity: the reference offers `adamw_8bit_bnb` through bitsandbytes'
CUDA kernels (/root/reference/trlx/utils/__init__.py:104-123,
accelerate_base_trainer.py:183-191). The TPU-native shape is the same
math with the moment states held in int8 + per-block fp32 absmax scales
(block 256, bnb's default): m is symmetric int8, v (non-negative) uses
the positive half. Dequantize -> fused adam update -> requantize runs
inside the jitted train step; XLA fuses the (de)quantization into the
update elementwise pass, so the win is the 4x smaller optimizer state in
HBM (the dominant term beyond params for fsdp-sharded training), not
kernel time.
"""

from __future__ import annotations

from typing import NamedTuple

import flax
import jax
import jax.numpy as jnp
import optax

BLOCK = 256


@flax.struct.dataclass
class Q8:
    q: jnp.ndarray  # int8 payload, flattened + padded to BLOCK
    scale: jnp.ndarray  # f32 per-block absmax
    shape: tuple = flax.struct.field(pytree_node=False)  # original (static)


def _quant_blocks(blocks: jnp.ndarray):
    """[n, BLOCK] fp32 -> (int8 payload, fp32 per-block absmax)."""
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    norm = jnp.abs(blocks) / jnp.maximum(scale, 1e-30)
    q = jnp.round(jnp.sign(blocks) * jnp.sqrt(norm) * 127.0)
    return q.astype(jnp.int8), scale[:, 0]


def _deq_blocks(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    u = q.astype(jnp.float32) / 127.0
    return jnp.sign(u) * u * u * scale[:, None]


def _to_blocks(x: jnp.ndarray) -> jnp.ndarray:
    flat = x.reshape(-1)
    pad = (-flat.size) % BLOCK
    return jnp.pad(flat, (0, pad)).reshape(-1, BLOCK)


def _quantize(x: jnp.ndarray) -> Q8:
    """Blockwise companded int8: q = sign * 127 * sqrt(|x| / absmax).

    The sqrt companding matches bitsandbytes' non-linear dynamic map in
    spirit: Adam's second moment spans orders of magnitude within one
    block, and a LINEAR absmax code wipes out the small entries, which
    visibly corrupts the update direction (sqrt(vhat) sits in the
    denominator)."""
    q, scale = _quant_blocks(_to_blocks(x.astype(jnp.float32)))
    return Q8(q, scale, x.shape)


def _dequantize(s: Q8) -> jnp.ndarray:
    flat = _deq_blocks(s.q, s.scale).reshape(-1)
    n = 1
    for d in s.shape:
        n *= d
    return flat[:n].reshape(s.shape)


class Adam8bitState(NamedTuple):
    count: jnp.ndarray
    m: optax.Params  # tree of Q8
    v: optax.Params  # tree of Q8


def _init_adam8bit_state(params) -> Adam8bitState:
    # m and v must be INDEPENDENT buffers: sharing one quantized-zeros
    # tree between them makes a donated state donate each buffer twice
    # (Execute() rejects `f(donate(a), donate(a))`)
    def zeros(p):
        return _quantize(jnp.zeros(p.shape, jnp.float32))

    return Adam8bitState(
        count=jnp.zeros([], jnp.int32),
        m=jax.tree_util.tree_map(zeros, params),
        v=jax.tree_util.tree_map(zeros, params),
    )


def scale_by_adam_8bit(
    b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
    step_dtype=None,
):
    """optax transformation holding both Adam moments in blockwise int8.

    `step_dtype`: dtype of the emitted updates tree. None (default)
    follows the gradient's dtype — bf16-grad callers get a bf16 updates
    tree (the memory-tight large-model behavior). Pass jnp.float32 to
    pin fp32 steps regardless of gradient precision."""

    init = _init_adam8bit_state

    def update(updates, state, params=None):
        count = state.count + 1

        def one(g, mq, vq):
            out_dtype = step_dtype if step_dtype is not None else g.dtype
            g = g.astype(jnp.float32)
            m = b1 * _dequantize(mq) + (1 - b1) * g
            v = b2 * _dequantize(vq) + (1 - b2) * g * g
            mhat = m / (1 - b1 ** count.astype(jnp.float32))
            vhat = v / (1 - b2 ** count.astype(jnp.float32))
            step = mhat / (jnp.sqrt(vhat) + eps)
            # emit the step in the grad's dtype: moment math stays fp32,
            # but a bf16-grad caller (memory-tight large models) gets a
            # bf16 updates tree — the step is O(1)-scaled, so bf16's
            # ~0.4% relative error is noise next to int8 moment states,
            # and optax.apply_updates promotes back to fp32 params
            return step.astype(out_dtype), _quantize(m), _quantize(v)

        flat_u, tdef = jax.tree_util.tree_flatten(updates)
        flat_m = tdef.flatten_up_to(state.m)
        flat_v = tdef.flatten_up_to(state.v)
        out = [one(g, m, v) for g, m, v in zip(flat_u, flat_m, flat_v)]
        steps = tdef.unflatten([o[0] for o in out])
        new_m = tdef.unflatten([o[1] for o in out])
        new_v = tdef.unflatten([o[2] for o in out])
        return steps, Adam8bitState(count=count, m=new_m, v=new_v)

    return optax.GradientTransformation(init, update)


def adamw_8bit(
    learning_rate, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
    weight_decay: float = 0.0,
):
    """AdamW with int8 moment states (drop-in for optax.adamw)."""
    chain = [scale_by_adam_8bit(b1=b1, b2=b2, eps=eps)]
    if weight_decay:
        chain.append(optax.add_decayed_weights(weight_decay))
    chain.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*chain)


# elements of fp32 temporaries the fused apply allows live per leaf:
# 2^22 * 4 B = 16 MB per array, a handful of arrays in flight
_FUSED_CHUNK_ELEMS = 1 << 22


def fused_adamw_8bit_update(
    params,
    grads,
    state: Adam8bitState,
    learning_rate: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    mask=None,
):
    """One fused AdamW step over int8 moments: returns (new_params,
    new_state) directly, never materializing an fp32 moment OR updates
    tree — the dequantize -> moment update -> requantize -> parameter
    apply chain streams through a `lax.scan` over block chunks per leaf.

    This is what bitsandbytes' fused CUDA kernel does (the reference's
    `adamw_8bit_bnb` row, ref trlx/utils/__init__.py:104-123): the
    optax-style `scale_by_adam_8bit` keeps the standard updates-tree
    contract, but at billion-parameter scale the fp32 temporaries of
    that contract (moments + updates, ~3 full fp32 copies in flight)
    are exactly what doesn't fit next to fp32 master params on a 16 GB
    chip. Donate params+state into the jit that calls this and the whole
    optimizer phase runs in O(chunk) extra memory.

    `grads` may be lower precision (bf16): moment math runs fp32 per
    chunk regardless, and the apply writes fp32 master params.

    `mask` (optional {0,1} update-multiplier tree, broadcastable per
    leaf — the trainers' freeze masks): applied INSIDE the streaming
    chunk loop (`p - lr*mask*step`), so freezing costs O(chunk) extra
    memory. The previous design blended frozen values back AFTER the
    apply, which held old params + new params + blended params — three
    fp32 trees, 10.6 GB of transient HBM at 1.3B and the difference
    between the at-scale recipe fitting a 16 GB chip or OOMing by
    ~0.5 GB (measured). A whole-leaf zero mask skips the leaf entirely
    (no moment updates either — the reference's frozen params are
    excluded from optimizer param groups the same way).
    """
    count = state.count + 1
    c = count.astype(jnp.float32)
    bc1 = 1 - b1 ** c
    bc2 = 1 - b2 ** c
    lr = jnp.asarray(learning_rate, jnp.float32)

    def one(p, g, mq, vq, m):
        if m is not None and jnp.ndim(m) == 0:
            if float(m) == 0.0:  # frozen leaf: untouched params AND moments
                return p, mq, vq
            m = None  # scalar 1: no masking needed
        shape, size, dtype = p.shape, p.size, p.dtype
        pb = _to_blocks(p)
        gb = _to_blocks(g)
        nb = pb.shape[0]
        mb = None  # [nb] per-block mask scalars, or [nb, BLOCK] elementwise
        if m is not None:
            import numpy as _np

            tail = int(_np.prod(shape[1:], dtype=_np.int64)) if len(shape) > 1 else 1
            if (
                all(d == 1 for d in _np.shape(m)[1:])
                and _np.shape(m)[0] == shape[0]
                and tail % BLOCK == 0
            ):
                # layer masks [L, 1, ...]: constant within every block
                # (per-layer tail divides the block size), so ONE scalar
                # per block suffices — 6 MB at 1.3B where a broadcast
                # elementwise mask would be a 1.6 GB fp32 transient per
                # large leaf (measured OOM)
                layer_ix = (jnp.arange(nb) * BLOCK) // tail
                mb = jnp.ravel(jnp.asarray(m, jnp.float32))[layer_ix]
            else:
                mb = _to_blocks(
                    jnp.broadcast_to(jnp.asarray(m, jnp.float32), shape)
                )
        # pad the block count up to a whole number of target-size chunks
        # (an exact-divisor search can collapse to huge chunks — e.g. a
        # prime block count would force ONE full-leaf fp32 chunk, which
        # defeats the O(chunk) memory bound this function exists for);
        # the pad rows quantize zeros and are sliced off below
        cb = max(1, _FUSED_CHUNK_ELEMS // BLOCK)
        n_chunks = -(-nb // cb)
        pad_rows = n_chunks * cb - nb

        def padb(x):
            if not pad_rows:
                return x
            widths = ((0, pad_rows),) + ((0, 0),) * (x.ndim - 1)
            return jnp.pad(x, widths)

        pb, gb = padb(pb), padb(gb)
        if mb is not None:
            mb = padb(mb)
        mq_q, mq_s = padb(mq.q), padb(mq.scale)
        vq_q, vq_s = padb(vq.q), padb(vq.scale)

        def body(_, xs):
            if mb is not None:
                p_c, g_c, mq_c, ms_c, vq_c, vs_c, m_c = xs
            else:
                p_c, g_c, mq_c, ms_c, vq_c, vs_c = xs
                m_c = None
            g32 = g_c.astype(jnp.float32)
            m = b1 * _deq_blocks(mq_c, ms_c) + (1 - b1) * g32
            v = b2 * _deq_blocks(vq_c, vs_c) + (1 - b2) * g32 * g32
            step = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            p32 = p_c.astype(jnp.float32)
            if weight_decay:
                step = step + weight_decay * p32
            if m_c is not None:
                step = step * (m_c[:, None] if m_c.ndim == 1 else m_c)
            new_p = (p32 - lr * step).astype(dtype)
            nmq, nms = _quant_blocks(m)
            nvq, nvs = _quant_blocks(v)
            return None, (new_p, nmq, nms, nvq, nvs)

        chunk = lambda x: x.reshape((n_chunks, cb) + x.shape[1:])
        xs = (
            chunk(pb), chunk(gb), chunk(mq_q), chunk(mq_s),
            chunk(vq_q), chunk(vq_s),
        )
        if mb is not None:
            xs = xs + (chunk(mb),)
        _, (new_p, nmq, nms, nvq, nvs) = jax.lax.scan(body, None, xs)
        new_p = new_p.reshape(-1)[:size].reshape(shape)
        # strip the chunk-pad rows so state shapes match init's exactly
        return (
            new_p,
            Q8(nmq.reshape(-1, BLOCK)[:nb], nms.reshape(-1)[:nb], shape),
            Q8(nvq.reshape(-1, BLOCK)[:nb], nvs.reshape(-1)[:nb], shape),
        )

    flat_p, tdef = jax.tree_util.tree_flatten(params)
    flat_g = tdef.flatten_up_to(grads)
    flat_m = tdef.flatten_up_to(state.m)
    flat_v = tdef.flatten_up_to(state.v)
    flat_mask = (
        tdef.flatten_up_to(mask) if mask is not None else [None] * len(flat_p)
    )
    out = [
        one(p, g, m, v, mk)
        for p, g, m, v, mk in zip(flat_p, flat_g, flat_m, flat_v, flat_mask)
    ]
    return (
        tdef.unflatten([o[0] for o in out]),
        Adam8bitState(
            count=count,
            m=tdef.unflatten([o[1] for o in out]),
            v=tdef.unflatten([o[2] for o in out]),
        ),
    )


class FusedAdamW8bit:
    """Registry-wirable fused variant: holds the AdamW hyperparameters
    and exposes `init` (optax-shaped, so `init_sharded_opt_state` and
    state checkpointing work unchanged) plus `fused_apply(params, grads,
    state) -> (new_params, new_state)`, which the trainers' step uses
    instead of the update/apply_updates pair whenever present.

    Select with `optimizer.name: adamw_8bit_fused` in a TRLConfig — the
    memory-tight large-model recipe (docs/benchmarks.md) reachable from
    config, not just hand-rolled steps. `learning_rate` may be an optax
    schedule; it is evaluated at the pre-increment step count, matching
    `optax.scale_by_learning_rate`'s cadence.
    """

    def __init__(self, learning_rate, b1: float = 0.9, b2: float = 0.999,
                 eps: float = 1e-8, weight_decay: float = 0.0):
        self.learning_rate = learning_rate
        self.b1, self.b2, self.eps = b1, b2, eps
        self.weight_decay = weight_decay

    def init(self, params) -> Adam8bitState:
        return _init_adam8bit_state(params)

    def update(self, grads, state, params=None):
        """optax-contract fallback so generic consumers (optax.chain,
        clipping wrappers, anything that composes transformations) still
        work: runs the fused step and returns the parameter DELTA as the
        updates tree. This materializes one extra params-sized tree —
        callers that can, should use `fused_apply` (the trainers do)."""
        if params is None:
            raise ValueError(
                "FusedAdamW8bit.update needs `params` (AdamW applies "
                "weight decay and writes parameters directly); pass "
                "params or use fused_apply(params, grads, state)"
            )
        new_params, new_state = self.fused_apply(params, grads, state)
        updates = jax.tree_util.tree_map(
            lambda n, p: (n - p).astype(p.dtype), new_params, params
        )
        return updates, new_state

    def fused_apply(self, params, grads, state: Adam8bitState, mask=None):
        lr = (
            self.learning_rate(state.count)
            if callable(self.learning_rate)
            else self.learning_rate
        )
        return fused_adamw_8bit_update(
            params, grads, state, lr, b1=self.b1, b2=self.b2, eps=self.eps,
            weight_decay=self.weight_decay, mask=mask,
        )
