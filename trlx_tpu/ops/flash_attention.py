"""Pallas fused causal attention for TPU — forward AND backward.

The reference leans on flash/fused attention inside its native deps
(SURVEY.md §2.9 last row — NeMo/HF kernels). Here the fused kernel is
first-party Pallas: per (batch*head, q-block) grid cell, scores are
computed against key/value *chunks* with an online softmax, so VMEM
holds only [block_q, chunk] tiles — the [B, H, T, S] probability tensor
never exists anywhere, which is the HBM-bandwidth win on TPU (the MXU
runs the two matmuls back to back from VMEM).

Backward is fused too (flash-style): the forward emits per-row softmax
stats, and two pallas kernels recompute probabilities chunkwise from
(q, k, m, l) to produce dq and (dk, dv). This is what makes 8k+ token
*training* practical: an XLA recompute path spills a multi-GB score
tensor per layer.

The softmax stats are saved as (m, l) SEPARATELY, not lse = m + log l:
fully-masked rows (pure-padding queries) have m = NEG_INF and the fp32
sum would absorb log(l), breaking the backward's probability
reconstruction. With (m, l), p = exp(s - m) / l reproduces the
forward's uniform distribution on those rows exactly, and ds is zeroed
at masked entries so gradients match the XLA where()-mask reference.

Enable with `TransformerConfig(attention_impl="pallas")`; CPU tests run
the kernels in interpreter mode automatically.

VMEM budget: full-length K/V (or Q/dO) rows live in VMEM in bf16
(~1 MB per 8k tokens at D=64) while fp32 tiles are [block, chunk] —
bounded regardless of sequence length. Sequences beyond ~32k tokens
should shard the sequence instead (ring attention,
ops/ring_attention.py)."""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
import numpy as np
from jax.experimental import pallas as pl

from trlx_tpu.ops.common import interpret_mode as _interpret
from trlx_tpu.ops.common import pick_block as _pick_block

NEG_INF = -1e30
# key/query chunk for the in-kernel loops: each fp32 score tile is
# [block, CHUNK]. 1024 runs the 8k fwd+bwd ~3x faster than 512 on v5e
# (better MXU occupancy per DMA) while keeping tiles ~1 MB in VMEM.
CHUNK = 1024


def _attention_reference(q, k, v, key_mask, causal: bool, sm_scale: float):
    """Plain XLA attention (numerics oracle for tests). Accepts GQA
    shapes (k/v with fewer heads) by repeating kv heads — the same thing
    transformer.py's XLA path does."""
    if k.shape[1] != q.shape[1]:
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    T, S = s.shape[-2], s.shape[-1]
    if causal:
        qi = jnp.arange(T)[:, None] + (S - T)
        s = jnp.where(qi >= jnp.arange(S)[None, :], s, NEG_INF)
    if key_mask is not None:
        s = jnp.where(key_mask[:, None, None, :] > 0, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _tile_valid(bq, ck, row0, col0, causal):
    """validity of a [bq, ck] score tile whose global top-left is
    (row0, col0) in causal coordinates (rows already q_offset-shifted)."""
    if not causal:
        return jnp.ones((bq, ck), jnp.bool_)
    rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (bq, ck), 0)
    cols = col0 + jax.lax.broadcasted_iota(jnp.int32, (bq, ck), 1)
    return rows >= cols


def _flash_kernel(
    q_ref, k_ref, v_ref, mask_ref, o_ref, m_ref, l_ref,
    *, sm_scale, causal, q_offset, n_chunks, ck,
):
    bq = q_ref.shape[1]
    D = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32)  # [Bq, D]
    row0 = pl.program_id(1) * bq + q_offset

    def body(j, carry):
        o_acc, m_run, l_run = carry
        k_c = k_ref[0, pl.ds(j * ck, ck), :].astype(jnp.float32)  # [ck, D]
        v_c = v_ref[0, pl.ds(j * ck, ck), :].astype(jnp.float32)
        mk = mask_ref[0, 0, pl.ds(j * ck, ck)]  # [ck]
        s = jax.lax.dot_general(
            q, k_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [Bq, ck]
        valid = _tile_valid(bq, ck, row0, j * ck, causal) & (mk[None, :] > 0)
        s = jnp.where(valid, s, NEG_INF)

        m_new = jnp.maximum(m_run, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_run - m_new)
        p = jnp.exp(s - m_new)  # [Bq, ck]
        l_new = l_run * corr + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o_acc * corr + jax.lax.dot_general(
            p, v_c, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((bq, D), jnp.float32)
    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, n_chunks, body, (o0, m0, l0))
    o_ref[0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    m_ref[0] = m
    l_ref[0] = l


def _kv_head_index(H: int, Hkv: int):
    """Grid-id -> kv row map for [B*Hkv, S, D] k/v arrays when the grid
    runs over B*H query heads: query head h reads kv head h // (H//Hkv)
    (grouped-query attention; identity when Hkv == H)."""
    rep = H // Hkv

    def ix(bh, qi):
        return ((bh // H) * Hkv + (bh % H) // rep, 0, 0)

    return ix


def _flash_forward(q, k, v, key_mask, causal, sm_scale, block_q,
                   with_stats=False, q_offset=None):
    B, H, T, D = q.shape
    Hkv, S = k.shape[1], k.shape[2]
    if H % Hkv:
        raise ValueError(f"n_head={H} not a multiple of n_kv_head={Hkv}")
    if q_offset is None:
        q_offset = S - T  # right-aligned queries (teacher-forced default)
    if key_mask is None:
        key_mask = jnp.ones((B, S), jnp.int32)
    bq = _pick_block(T, block_q)
    ck = _pick_block(S, CHUNK)
    grid = (B * H, T // bq)

    qr = q.reshape(B * H, T, D)
    # GQA: k/v stay at Hkv heads — never materialized repeated; the
    # BlockSpec index map routes each q head's grid cells to its group's
    # kv rows, so HBM reads per kv head happen once per GROUP, which is
    # the bandwidth saving GQA exists for
    kr = k.reshape(B * Hkv, S, D)
    vr = v.reshape(B * Hkv, S, D)
    kv_ix = _kv_head_index(H, Hkv)

    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal, q_offset=q_offset,
        n_chunks=S // ck, ck=ck,
    )
    out, m, l = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, D), kv_ix),
            pl.BlockSpec((1, S, D), kv_ix),
            # [B, 1, S] so the block's trailing two dims (1, S) equal the
            # array dims — Mosaic requires trailing block dims divisible
            # by (8, 128) OR equal to the array's (a bare (1, S) block
            # over [B, S] fails to lower on real TPU)
            pl.BlockSpec((1, 1, S), lambda bh, qi: (bh // H, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, 1), jnp.float32),
            jax.ShapeDtypeStruct((B * H, T, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(qr, kr, vr, key_mask.astype(jnp.int32)[:, None, :])
    out = out.reshape(B, H, T, D)
    if with_stats:
        return out, m, l
    return out


def _dq_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, m_ref, l_ref, delta_ref, dq_ref,
    *, sm_scale, causal, q_offset, n_chunks, ck,
):
    bq = q_ref.shape[1]
    D = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32)  # [Bq, D]
    do = do_ref[0].astype(jnp.float32)  # [Bq, D]
    m = m_ref[0]  # [Bq, 1]
    l = jnp.maximum(l_ref[0], 1e-30)
    delta = delta_ref[0]  # [Bq, 1]
    row0 = pl.program_id(1) * bq + q_offset

    def body(j, dq_acc):
        k_c = k_ref[0, pl.ds(j * ck, ck), :].astype(jnp.float32)  # [ck, D]
        v_c = v_ref[0, pl.ds(j * ck, ck), :].astype(jnp.float32)
        mk = mask_ref[0, 0, pl.ds(j * ck, ck)]
        s = jax.lax.dot_general(
            q, k_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [Bq, ck]
        valid = _tile_valid(bq, ck, row0, j * ck, causal) & (mk[None, :] > 0)
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - m) / l  # [Bq, ck]
        dp = jax.lax.dot_general(
            do, v_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [Bq, ck]
        # masked entries carry no gradient into s (the reference's
        # where() routes their cotangent to the NEG_INF constant);
        # explicit zeroing matters on fully-masked rows where p is
        # uniform, not ~0
        ds = jnp.where(valid, p * (dp - delta) * sm_scale, 0.0)
        return dq_acc + jax.lax.dot_general(
            ds, k_c, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = jax.lax.fori_loop(0, n_chunks, body, jnp.zeros((bq, D), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, m_ref, l_ref, delta_ref, dk_ref, dv_ref,
    *, sm_scale, causal, q_offset, n_chunks, cq, q_chunks_per_head,
):
    """dk/dv for one key block. Works in TRANSPOSED orientation
    ([Bk, cq] score tiles) so the per-row stats stream in lane-major
    [1, T] layout — a [T, 1] operand would be lane-padded to [T, 128]
    in VMEM (4 MB per stat at 8k tokens), which blows the budget.

    GQA: the grid runs over B*Hkv and the q/do/stat refs carry the whole
    GROUP's rows ([rep*T] where rep = n_head // n_kv_head, heads
    contiguous), so each group member's contribution accumulates into
    the same (dk, dv) — the sum-over-group that jnp.repeat's transpose
    would otherwise do as a separate XLA pass. The chunk loop walks all
    rep*T rows; a row's causal position is its index within its own
    head, recovered per chunk as (j % q_chunks_per_head) * cq since cq
    divides T (chunks never straddle heads)."""
    bk = k_ref.shape[1]
    D = k_ref.shape[2]
    k = k_ref[0].astype(jnp.float32)  # [Bk, D]
    v = v_ref[0].astype(jnp.float32)
    col0 = pl.program_id(1) * bk
    mk = mask_ref[0, 0, pl.ds(col0, bk)]  # [Bk]

    def body(j, carry):
        dk_acc, dv_acc = carry
        q_c = q_ref[0, pl.ds(j * cq, cq), :].astype(jnp.float32)  # [cq, D]
        do_c = do_ref[0, pl.ds(j * cq, cq), :].astype(jnp.float32)
        m_c = m_ref[0, 0, pl.ds(j * cq, cq)]  # [cq] (lane vector)
        l_c = jnp.maximum(l_ref[0, 0, pl.ds(j * cq, cq)], 1e-30)
        delta_c = delta_ref[0, 0, pl.ds(j * cq, cq)]
        s_t = jax.lax.dot_general(
            k, q_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [Bk, cq]
        rows = col0 + jax.lax.broadcasted_iota(jnp.int32, (bk, cq), 0)  # key idx
        pos0 = (j % q_chunks_per_head) * cq  # q position within its head
        cols = pos0 + q_offset + jax.lax.broadcasted_iota(jnp.int32, (bk, cq), 1)
        valid = (cols >= rows) if causal else jnp.ones((bk, cq), jnp.bool_)
        valid = valid & (mk[:, None] > 0)
        s_t = jnp.where(valid, s_t, NEG_INF)
        p_t = jnp.exp(s_t - m_c[None, :]) / l_c[None, :]  # [Bk, cq]
        dv_new = dv_acc + jax.lax.dot_general(
            p_t, do_c, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [Bk, D]
        dp_t = jax.lax.dot_general(
            v, do_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [Bk, cq]
        ds_t = jnp.where(valid, p_t * (dp_t - delta_c[None, :]) * sm_scale, 0.0)
        dk_new = dk_acc + jax.lax.dot_general(
            ds_t, q_c, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [Bk, D]
        return dk_new, dv_new

    z = jnp.zeros((bk, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, n_chunks, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, key_mask, o, m, l, g, causal, sm_scale, block_q,
                    q_offset=None):
    B, H, T, D = q.shape
    Hkv, S = k.shape[1], k.shape[2]
    rep = H // Hkv
    if q_offset is None:
        q_offset = S - T
    if key_mask is None:
        key_mask = jnp.ones((B, S), jnp.int32)
    mask3 = key_mask.astype(jnp.int32)[:, None, :]

    qr = q.reshape(B * H, T, D)
    kr = k.reshape(B * Hkv, S, D)
    vr = v.reshape(B * Hkv, S, D)
    kv_ix = _kv_head_index(H, Hkv)
    dor = g.reshape(B * H, T, D)
    # delta_i = rowsum(dO_i * O_i): tiny elementwise pass, fine in XLA
    delta = jnp.sum(
        dor.astype(jnp.float32) * o.reshape(B * H, T, D).astype(jnp.float32),
        axis=-1, keepdims=True,
    )  # [BH, T, 1]

    bq = _pick_block(T, block_q)
    ck = _pick_block(S, CHUNK)
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, sm_scale=sm_scale, causal=causal, q_offset=q_offset,
            n_chunks=S // ck, ck=ck,
        ),
        grid=(B * H, T // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, D), kv_ix),
            pl.BlockSpec((1, S, D), kv_ix),
            pl.BlockSpec((1, 1, S), lambda bh, qi: (bh // H, 0, 0)),
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=_interpret(),
    )(qr, kr, vr, mask3, dor, m, l, delta)

    bk = _pick_block(S, block_q)
    cq = _pick_block(T, CHUNK)
    # GQA: one dkv grid row per KV head; the group's q/do/stat rows are
    # flattened head-major ([B, Hkv, rep, T, ...] -> [B*Hkv, rep*T, ...])
    # so the kernel's chunk loop accumulates the whole group into its kv
    # head's (dk, dv) — no repeated kv materialization, no XLA reduce
    qg = q.reshape(B * Hkv, rep * T, D)
    dog = g.reshape(B * Hkv, rep * T, D)
    # lane-major stat views for the dkv kernel (see its docstring)
    m_t = m.reshape(B * Hkv, 1, rep * T)
    l_t = l.reshape(B * Hkv, 1, rep * T)
    delta_t = delta.reshape(B * Hkv, 1, rep * T)
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, sm_scale=sm_scale, causal=causal, q_offset=q_offset,
            n_chunks=rep * T // cq, cq=cq, q_chunks_per_head=T // cq,
        ),
        grid=(B * Hkv, S // bk),
        in_specs=[
            pl.BlockSpec((1, rep * T, D), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, 1, S), lambda bh, ki: (bh // Hkv, 0, 0)),
            pl.BlockSpec((1, rep * T, D), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, 1, rep * T), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, 1, rep * T), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, 1, rep * T), lambda bh, ki: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * Hkv, S, D), k.dtype),
            jax.ShapeDtypeStruct((B * Hkv, S, D), v.dtype),
        ],
        interpret=_interpret(),
    )(qg, kr, vr, mask3, dog, m_t, l_t, delta_t)

    return (
        dq.reshape(B, H, T, D),
        dk.reshape(B, Hkv, S, D),
        dv.reshape(B, Hkv, S, D),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def flash_attention(q, k, v, key_mask, causal=True, sm_scale=None, block_q=256,
                    q_offset=None):
    """Fused attention. q: [B, H, T, D]; k/v: [B, Hkv, S, D] with
    Hkv | H (grouped-query attention — pass kv heads UNREPEATED, the
    kernels route each q head to its group's kv rows and accumulate the
    group's dk/dv natively); key_mask: [B, S] (1=real).

    Causality compares PHYSICAL slots. `q_offset` (STATIC int) is the
    slot of query row 0; the default None means right-aligned queries
    (q_offset = S - T, the teacher-forced / hydra-branch layout). A
    KV-cache PREFILL passes its static write index instead: queries
    occupy slots [q_offset, q_offset + T) against the full cache length
    S, with unwritten future slots excluded via key_mask.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_forward(q, k, v, key_mask, causal, sm_scale, block_q,
                          q_offset=q_offset)


def _fwd(q, k, v, key_mask, causal, sm_scale, block_q, q_offset):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    out, m, l = _flash_forward(
        q, k, v, key_mask, causal, sm_scale, block_q, with_stats=True,
        q_offset=q_offset,
    )
    # named so a remat policy can pin the kernel's residuals: under
    # jax.checkpoint the custom-VJP primal re-executes to rebuild
    # residuals — i.e. the forward KERNEL runs again in the backward
    # pass. `save_attn` (ops/remat.py) saves exactly (out, m, l); q/k/v
    # rematerialize from their projection matmuls, which is cheap next
    # to a full online-softmax sweep.
    out = checkpoint_name(out, "flash_out")
    m = checkpoint_name(m, "flash_m")
    l = checkpoint_name(l, "flash_l")
    return out, (q, k, v, key_mask, out, m, l)


def _bwd(causal, sm_scale, block_q, q_offset, res, g):
    q, k, v, key_mask, o, m, l = res
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    dq, dk, dv = _flash_backward(
        q, k, v, key_mask, o, m, l, g, causal, sm_scale, block_q,
        q_offset=q_offset,
    )
    return dq, dk, dv, None


flash_attention.defvjp(_fwd, _bwd)


# ---------------------------------------------------------------------------
# Bias-carrying variant (T5 relative position bias).
#
# T5 self-attention adds a LEARNED additive bias to the scores (and uses
# no 1/sqrt(d) scale). The bias is batch-invariant ([H, T, S]) and shared
# across the layer stack, so it is materialized ONCE per forward while
# the per-layer [B, H, T, S] score/probability tensors still never
# exist — the structural memory win stands. The backward returns dbias
# (= ds summed over batch, accumulated in-kernel across the grid's
# batch-innermost axis), so the rel_bias table trains exactly as on the
# XLA path. Scale note: the dense bias costs T*S fp32 once (2 GB/head-8
# at 32k) — beyond that, recomputing buckets in-kernel from the tiny
# [n_buckets, H] table (Toeplitz structure) is the planned follow-up.
# No GQA here (T5 has none): Hkv must equal H.
# ---------------------------------------------------------------------------


def _flash_bias_kernel(
    q_ref, k_ref, v_ref, mask_ref, bias_ref, o_ref, m_ref, l_ref,
    *, sm_scale, causal, n_chunks, ck,
):
    bq = q_ref.shape[1]
    D = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32)
    row0 = pl.program_id(1) * bq

    def body(j, carry):
        o_acc, m_run, l_run = carry
        k_c = k_ref[0, pl.ds(j * ck, ck), :].astype(jnp.float32)
        v_c = v_ref[0, pl.ds(j * ck, ck), :].astype(jnp.float32)
        mk = mask_ref[0, 0, pl.ds(j * ck, ck)]
        s = jax.lax.dot_general(
            q, k_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        s = s + bias_ref[0, :, pl.ds(j * ck, ck)]
        valid = _tile_valid(bq, ck, row0, j * ck, causal) & (mk[None, :] > 0)
        s = jnp.where(valid, s, NEG_INF)

        m_new = jnp.maximum(m_run, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_run - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_run * corr + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o_acc * corr + jax.lax.dot_general(
            p, v_c, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((bq, D), jnp.float32)
    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, n_chunks, body, (o0, m0, l0))
    o_ref[0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    m_ref[0] = m
    l_ref[0] = l


def _bias_block_q(block_q: int, S: int) -> int:
    """Query block for the bias variants, shrunk with key length: each
    grid cell holds a [bq, S] fp32 bias strip (and the dq kernel a
    second [bq, S] dbias block) in VMEM, so bq must scale down as S
    grows — [128, 8192] alone is 4 MB and measured over the 16 MB
    scoped-vmem limit at 8k with the rest of the working set (double
    -buffered strips + the chunk loop's score tiles); 1 MB strips
    (bq=32 at 8k) fit with headroom."""
    return min(block_q, max(8, (1 << 20) // (4 * S)))


def _flash_bias_forward(q, k, v, key_mask, bias, causal, sm_scale, block_q,
                        with_stats=False):
    B, H, T, D = q.shape
    S = k.shape[2]
    if k.shape[1] != H:
        raise ValueError("flash_attention_bias does not support GQA")
    if key_mask is None:
        key_mask = jnp.ones((B, S), jnp.int32)
    bq = _pick_block(T, _bias_block_q(block_q, S))
    ck = _pick_block(S, CHUNK)
    grid = (B * H, T // bq)
    kernel = functools.partial(
        _flash_bias_kernel, sm_scale=sm_scale, causal=causal,
        n_chunks=S // ck, ck=ck,
    )
    out, m, l = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda bh, qi: (bh // H, 0, 0)),
            # bias strip [bq, S] fp32 in VMEM — the reason the bias
            # variants default to block_q=128 (4 MB at 8k)
            pl.BlockSpec((1, bq, S), lambda bh, qi: (bh % H, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, 1), jnp.float32),
            jax.ShapeDtypeStruct((B * H, T, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(
        q.reshape(B * H, T, D), k.reshape(B * H, S, D), v.reshape(B * H, S, D),
        key_mask.astype(jnp.int32)[:, None, :], bias.astype(jnp.float32),
    )
    out = out.reshape(B, H, T, D)
    if with_stats:
        return out, m, l
    return out


def _dq_dbias_kernel(
    q_ref, k_ref, v_ref, mask_ref, bias_ref, do_ref, m_ref, l_ref, delta_ref,
    dq_ref, dbias_ref, *, sm_scale, causal, n_chunks, ck,
):
    """dq for one (head, q-block, batch) cell + dbias accumulated across
    the batch-innermost grid axis (consecutive revisits of the same
    output block, so pallas keeps it resident and flushes once)."""
    bq = q_ref.shape[1]
    D = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    m = m_ref[0]
    l = jnp.maximum(l_ref[0], 1e-30)
    delta = delta_ref[0]
    row0 = pl.program_id(1) * bq

    @pl.when(pl.program_id(2) == 0)
    def _init():
        dbias_ref[...] = jnp.zeros_like(dbias_ref)

    def body(j, dq_acc):
        k_c = k_ref[0, pl.ds(j * ck, ck), :].astype(jnp.float32)
        v_c = v_ref[0, pl.ds(j * ck, ck), :].astype(jnp.float32)
        mk = mask_ref[0, 0, pl.ds(j * ck, ck)]
        s = jax.lax.dot_general(
            q, k_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        s = s + bias_ref[0, :, pl.ds(j * ck, ck)]
        valid = _tile_valid(bq, ck, row0, j * ck, causal) & (mk[None, :] > 0)
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - m) / l
        dp = jax.lax.dot_general(
            do, v_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = jnp.where(valid, p * (dp - delta), 0.0)  # d(score+bias)
        dbias_ref[0, :, pl.ds(j * ck, ck)] += ds
        return dq_acc + sm_scale * jax.lax.dot_general(
            ds, k_c, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = jax.lax.fori_loop(0, n_chunks, body, jnp.zeros((bq, D), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_bias_kernel(
    q_ref, k_ref, v_ref, mask_ref, biasT_ref, do_ref, m_ref, l_ref, delta_ref,
    dk_ref, dv_ref, *, sm_scale, causal, cq,
):
    """dk/dv for one (head, key-block) pair, transposed orientation (see
    _dkv_kernel). Unlike the causal kernel, the q dimension is a GRID
    axis (innermost), not an in-kernel loop: the [bk, T] biasT strip the
    loop form needs in VMEM is 4 MB at 8k (measured over the scoped
    limit), while grid-blocked [bk, cq] bias tiles stay ~256 KB. dk/dv
    accumulate fp32 across the consecutive q-chunk revisits."""
    bk = k_ref.shape[1]
    j = pl.program_id(2)
    col0 = pl.program_id(1) * bk
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    mk = mask_ref[0, 0, pl.ds(col0, bk)]
    q_c = q_ref[0].astype(jnp.float32)  # [cq, D]
    do_c = do_ref[0].astype(jnp.float32)
    m_c = m_ref[0, 0]  # [cq]
    l_c = jnp.maximum(l_ref[0, 0], 1e-30)
    delta_c = delta_ref[0, 0]

    @pl.when(j == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    s_t = jax.lax.dot_general(
        k, q_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale
    s_t = s_t + biasT_ref[0]
    rows = col0 + jax.lax.broadcasted_iota(jnp.int32, (bk, cq), 0)
    cols = j * cq + jax.lax.broadcasted_iota(jnp.int32, (bk, cq), 1)
    valid = (cols >= rows) if causal else jnp.ones((bk, cq), jnp.bool_)
    valid = valid & (mk[:, None] > 0)
    s_t = jnp.where(valid, s_t, NEG_INF)
    p_t = jnp.exp(s_t - m_c[None, :]) / l_c[None, :]
    dv_ref[0] += jax.lax.dot_general(
        p_t, do_c, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dp_t = jax.lax.dot_general(
        v, do_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds_t = jnp.where(valid, p_t * (dp_t - delta_c[None, :]), 0.0)
    dk_ref[0] += sm_scale * jax.lax.dot_general(
        ds_t, q_c, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _flash_bias_backward(q, k, v, key_mask, bias, o, m, l, g, causal,
                         sm_scale, block_q):
    B, H, T, D = q.shape
    S = k.shape[2]
    if key_mask is None:
        key_mask = jnp.ones((B, S), jnp.int32)
    mask3 = key_mask.astype(jnp.int32)[:, None, :]
    bias32 = bias.astype(jnp.float32)

    qr = q.reshape(B * H, T, D)
    kr = k.reshape(B * H, S, D)
    vr = v.reshape(B * H, S, D)
    dor = g.reshape(B * H, T, D)
    delta = jnp.sum(
        dor.astype(jnp.float32) * o.reshape(B * H, T, D).astype(jnp.float32),
        axis=-1, keepdims=True,
    )

    bq = _pick_block(T, _bias_block_q(block_q, S))
    ck = _pick_block(S, CHUNK)
    # batch INNERMOST so the dbias output block (h, qi) is revisited on
    # consecutive grid steps, accumulating the sum over batch in VMEM
    dq, dbias = pl.pallas_call(
        functools.partial(
            _dq_dbias_kernel, sm_scale=sm_scale, causal=causal,
            n_chunks=S // ck, ck=ck,
        ),
        grid=(H, T // bq, B),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda h, qi, b: (b * H + h, qi, 0)),
            pl.BlockSpec((1, S, D), lambda h, qi, b: (b * H + h, 0, 0)),
            pl.BlockSpec((1, S, D), lambda h, qi, b: (b * H + h, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda h, qi, b: (b, 0, 0)),
            pl.BlockSpec((1, bq, S), lambda h, qi, b: (h, qi, 0)),
            pl.BlockSpec((1, bq, D), lambda h, qi, b: (b * H + h, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, qi, b: (b * H + h, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, qi, b: (b * H + h, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, qi, b: (b * H + h, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda h, qi, b: (b * H + h, qi, 0)),
            pl.BlockSpec((1, bq, S), lambda h, qi, b: (h, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((H, T, S), jnp.float32),
        ],
        interpret=_interpret(),
    )(qr, kr, vr, mask3, bias32, dor, m, l, delta)

    # key blocks stay at 128: the kernel's mask slice pl.ds(ki*bk, bk)
    # must be statically provable as 128-aligned (Mosaic requirement on
    # dynamic lane-dim indices); q-chunks are an innermost GRID axis so
    # bias rides in [bk, cq] tiles (see _dkv_bias_kernel docstring)
    bk = _pick_block(S, 128)
    cq = _pick_block(T, CHUNK)
    biasT = bias32.transpose(0, 2, 1)  # [H, S, T] for lane-major tiles
    m_t = m.reshape(B * H, 1, T)
    l_t = l.reshape(B * H, 1, T)
    delta_t = delta.reshape(B * H, 1, T)
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_bias_kernel, sm_scale=sm_scale, causal=causal, cq=cq,
        ),
        grid=(B * H, S // bk, T // cq),
        in_specs=[
            pl.BlockSpec((1, cq, D), lambda bh, ki, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, ki, j: (bh, ki, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, ki, j: (bh, ki, 0)),
            pl.BlockSpec((1, 1, S), lambda bh, ki, j: (bh // H, 0, 0)),
            pl.BlockSpec((1, bk, cq), lambda bh, ki, j: (bh % H, ki, j)),
            pl.BlockSpec((1, cq, D), lambda bh, ki, j: (bh, j, 0)),
            pl.BlockSpec((1, 1, cq), lambda bh, ki, j: (bh, 0, j)),
            pl.BlockSpec((1, 1, cq), lambda bh, ki, j: (bh, 0, j)),
            pl.BlockSpec((1, 1, cq), lambda bh, ki, j: (bh, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda bh, ki, j: (bh, ki, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, ki, j: (bh, ki, 0)),
        ],
        out_shape=[
            # fp32: dk/dv accumulate across q-chunk grid revisits
            jax.ShapeDtypeStruct((B * H, S, D), jnp.float32),
            jax.ShapeDtypeStruct((B * H, S, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(qr, kr, vr, mask3, biasT, dor, m_t, l_t, delta_t)

    return (
        dq.reshape(B, H, T, D),
        dk.reshape(B, H, S, D).astype(k.dtype),
        dv.reshape(B, H, S, D).astype(v.dtype),
        dbias.astype(bias.dtype),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def flash_attention_bias(q, k, v, key_mask, bias, causal=False,
                         sm_scale=1.0, block_q=128):
    """Fused attention with a learned additive bias (T5 relative
    position bias). q/k/v: [B, H, T|S, D] (no GQA); key_mask: [B, S];
    bias: [H, T, S], batch-invariant and DIFFERENTIABLE (the backward
    returns its gradient summed over batch). T5 semantics: sm_scale
    defaults to 1.0 (the scale is folded into T5's init), causality is
    optional (encoder False / decoder True), queries are assumed
    unpadded full-sequence (T == S layouts)."""
    return _flash_bias_forward(q, k, v, key_mask, bias, causal, sm_scale,
                               block_q)


def _bias_fwd(q, k, v, key_mask, bias, causal, sm_scale, block_q):
    out, m, l = _flash_bias_forward(
        q, k, v, key_mask, bias, causal, sm_scale, block_q, with_stats=True
    )
    out = checkpoint_name(out, "flash_out")
    m = checkpoint_name(m, "flash_m")
    l = checkpoint_name(l, "flash_l")
    return out, (q, k, v, key_mask, bias, out, m, l)


def _bias_bwd(causal, sm_scale, block_q, res, g):
    q, k, v, key_mask, bias, o, m, l = res
    dq, dk, dv, dbias = _flash_bias_backward(
        q, k, v, key_mask, bias, o, m, l, g, causal, sm_scale, block_q
    )
    return dq, dk, dv, None, dbias


flash_attention_bias.defvjp(_bias_fwd, _bias_bwd)
