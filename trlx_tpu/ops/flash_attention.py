"""Pallas fused causal attention for TPU.

The reference leans on flash/fused attention inside its native deps
(SURVEY.md §2.9 last row — NeMo/HF kernels). Here the fused kernel is
first-party Pallas: per (batch*head, q-block) grid cell the scores
[Bq, S] live only in VMEM — the [B, H, T, S] probability tensor never
touches HBM, which is the HBM-bandwidth win on TPU (the MXU does the two
matmuls back to back from VMEM).

Gradient story: the kernel carries a `jax.custom_vjp` whose backward
recomputes attention with plain XLA ops and differentiates that — the
training step pays the same FLOPs as the XLA path while every no-grad
forward (rollout generation prefill, the experience-scoring forward,
evaluation) runs the fused kernel. Enable with
`TransformerConfig(attention_impl="pallas")`; CPU tests run the kernel
in interpreter mode automatically.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _attention_reference(q, k, v, key_mask, causal: bool, sm_scale: float):
    """Plain XLA attention (backward-pass recompute + numerics oracle)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    T, S = s.shape[-2], s.shape[-1]
    if causal:
        qi = jnp.arange(T)[:, None] + (S - T)
        s = jnp.where(qi >= jnp.arange(S)[None, :], s, NEG_INF)
    if key_mask is not None:
        s = jnp.where(key_mask[:, None, None, :] > 0, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, sm_scale, causal, q_offset):
    q = q_ref[0].astype(jnp.float32)  # [Bq, D]
    k = k_ref[0].astype(jnp.float32)  # [S, D]
    v = v_ref[0].astype(jnp.float32)  # [S, D]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale  # [Bq, S]

    Bq, S = s.shape
    qi = pl.program_id(1)
    if causal:
        rows = qi * Bq + jax.lax.broadcasted_iota(jnp.int32, (Bq, S), 0) + q_offset
        cols = jax.lax.broadcasted_iota(jnp.int32, (Bq, S), 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    mask = mask_ref[0, 0]  # [S]
    s = jnp.where(mask[None, :] > 0, s, NEG_INF)

    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) / jnp.maximum(l, 1e-30)
    o_ref[0] = o.astype(o_ref.dtype)


def _flash_forward(q, k, v, key_mask, causal: bool, sm_scale: float, block_q: int):
    B, H, T, D = q.shape
    S = k.shape[2]
    if key_mask is None:
        key_mask = jnp.ones((B, S), jnp.int32)
    bq = min(block_q, T)
    while T % bq:
        bq //= 2
    grid = (B * H, T // bq)

    qr = q.reshape(B * H, T, D)
    kr = k.reshape(B * H, S, D)
    vr = v.reshape(B * H, S, D)

    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal, q_offset=S - T
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
            # [B, 1, S] so the block's trailing two dims (1, S) equal the
            # array dims — Mosaic requires trailing block dims divisible
            # by (8, 128) OR equal to the array's (a bare (1, S) block
            # over [B, S] fails to lower on real TPU)
            pl.BlockSpec((1, 1, S), lambda bh, qi: (bh // H, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=jax.default_backend() == "cpu",
    )(qr, kr, vr, key_mask.astype(jnp.int32)[:, None, :])
    return out.reshape(B, H, T, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention(q, k, v, key_mask, causal=True, sm_scale=None, block_q=128):
    """Fused attention. q/k/v: [B, H, T|S, D]; key_mask: [B, S] (1=real).

    Causality compares PHYSICAL slots with queries right-aligned against
    keys (q_offset = S - T), matching the transformer's slot semantics.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_forward(q, k, v, key_mask, causal, sm_scale, block_q)


def _fwd(q, k, v, key_mask, causal, sm_scale, block_q):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    out = _flash_forward(q, k, v, key_mask, causal, sm_scale, block_q)
    return out, (q, k, v, key_mask)


def _bwd(causal, sm_scale, block_q, res, g):
    q, k, v, key_mask = res
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _attention_reference(q_, k_, v_, key_mask, causal, sm_scale),
        q, k, v,
    )
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


flash_attention.defvjp(_fwd, _bwd)
