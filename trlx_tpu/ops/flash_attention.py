"""Pallas fused causal attention for TPU — forward AND backward.

The reference leans on flash/fused attention inside its native deps
(SURVEY.md §2.9 last row — NeMo/HF kernels). Here the fused kernel is
first-party Pallas: per (batch*head, q-block) grid cell, scores are
computed against key/value *chunks* with an online softmax, so VMEM
holds only [block_q, chunk] tiles — the [B, H, T, S] probability tensor
never exists anywhere, which is the HBM-bandwidth win on TPU (the MXU
runs the two matmuls back to back from VMEM).

Backward is fused too (flash-style): the forward emits per-row softmax
stats, and two pallas kernels recompute probabilities chunkwise from
(q, k, m, l) to produce dq and (dk, dv). This is what makes 8k+ token
*training* practical: an XLA recompute path spills a multi-GB score
tensor per layer.

The softmax stats are saved as (m, l) SEPARATELY, not lse = m + log l:
fully-masked rows (pure-padding queries) have m = NEG_INF and the fp32
sum would absorb log(l), breaking the backward's probability
reconstruction. With (m, l), p = exp(s - m) / l reproduces the
forward's uniform distribution on those rows exactly, and ds is zeroed
at masked entries so gradients match the XLA where()-mask reference.

Enable with `TransformerConfig(attention_impl="pallas")`; CPU tests run
the kernels in interpreter mode automatically.

VMEM budget: full-length K/V (or Q/dO) rows live in VMEM in bf16
(~1 MB per 8k tokens at D=64) while fp32 tiles are [block, chunk] —
bounded regardless of sequence length. Sequences beyond ~32k tokens
should shard the sequence instead (ring attention,
ops/ring_attention.py)."""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

NEG_INF = -1e30
# key/query chunk for the in-kernel loops: each fp32 score tile is
# [block, CHUNK]. 1024 runs the 8k fwd+bwd ~3x faster than 512 on v5e
# (better MXU occupancy per DMA) while keeping tiles ~1 MB in VMEM.
CHUNK = 1024


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _attention_reference(q, k, v, key_mask, causal: bool, sm_scale: float):
    """Plain XLA attention (numerics oracle for tests)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    T, S = s.shape[-2], s.shape[-1]
    if causal:
        qi = jnp.arange(T)[:, None] + (S - T)
        s = jnp.where(qi >= jnp.arange(S)[None, :], s, NEG_INF)
    if key_mask is not None:
        s = jnp.where(key_mask[:, None, None, :] > 0, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _pick_block(n: int, block: int) -> int:
    b = min(block, n)
    while n % b:
        b //= 2
    return b


def _tile_valid(bq, ck, row0, col0, causal):
    """validity of a [bq, ck] score tile whose global top-left is
    (row0, col0) in causal coordinates (rows already q_offset-shifted)."""
    if not causal:
        return jnp.ones((bq, ck), jnp.bool_)
    rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (bq, ck), 0)
    cols = col0 + jax.lax.broadcasted_iota(jnp.int32, (bq, ck), 1)
    return rows >= cols


def _flash_kernel(
    q_ref, k_ref, v_ref, mask_ref, o_ref, m_ref, l_ref,
    *, sm_scale, causal, q_offset, n_chunks, ck,
):
    bq = q_ref.shape[1]
    D = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32)  # [Bq, D]
    row0 = pl.program_id(1) * bq + q_offset

    def body(j, carry):
        o_acc, m_run, l_run = carry
        k_c = k_ref[0, pl.ds(j * ck, ck), :].astype(jnp.float32)  # [ck, D]
        v_c = v_ref[0, pl.ds(j * ck, ck), :].astype(jnp.float32)
        mk = mask_ref[0, 0, pl.ds(j * ck, ck)]  # [ck]
        s = jax.lax.dot_general(
            q, k_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [Bq, ck]
        valid = _tile_valid(bq, ck, row0, j * ck, causal) & (mk[None, :] > 0)
        s = jnp.where(valid, s, NEG_INF)

        m_new = jnp.maximum(m_run, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_run - m_new)
        p = jnp.exp(s - m_new)  # [Bq, ck]
        l_new = l_run * corr + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o_acc * corr + jax.lax.dot_general(
            p, v_c, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((bq, D), jnp.float32)
    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, n_chunks, body, (o0, m0, l0))
    o_ref[0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    m_ref[0] = m
    l_ref[0] = l


def _flash_forward(q, k, v, key_mask, causal, sm_scale, block_q, with_stats=False):
    B, H, T, D = q.shape
    S = k.shape[2]
    if key_mask is None:
        key_mask = jnp.ones((B, S), jnp.int32)
    bq = _pick_block(T, block_q)
    ck = _pick_block(S, CHUNK)
    grid = (B * H, T // bq)

    qr = q.reshape(B * H, T, D)
    kr = k.reshape(B * H, S, D)
    vr = v.reshape(B * H, S, D)

    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal, q_offset=S - T,
        n_chunks=S // ck, ck=ck,
    )
    out, m, l = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
            # [B, 1, S] so the block's trailing two dims (1, S) equal the
            # array dims — Mosaic requires trailing block dims divisible
            # by (8, 128) OR equal to the array's (a bare (1, S) block
            # over [B, S] fails to lower on real TPU)
            pl.BlockSpec((1, 1, S), lambda bh, qi: (bh // H, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, 1), jnp.float32),
            jax.ShapeDtypeStruct((B * H, T, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(qr, kr, vr, key_mask.astype(jnp.int32)[:, None, :])
    out = out.reshape(B, H, T, D)
    if with_stats:
        return out, m, l
    return out


def _dq_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, m_ref, l_ref, delta_ref, dq_ref,
    *, sm_scale, causal, q_offset, n_chunks, ck,
):
    bq = q_ref.shape[1]
    D = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32)  # [Bq, D]
    do = do_ref[0].astype(jnp.float32)  # [Bq, D]
    m = m_ref[0]  # [Bq, 1]
    l = jnp.maximum(l_ref[0], 1e-30)
    delta = delta_ref[0]  # [Bq, 1]
    row0 = pl.program_id(1) * bq + q_offset

    def body(j, dq_acc):
        k_c = k_ref[0, pl.ds(j * ck, ck), :].astype(jnp.float32)  # [ck, D]
        v_c = v_ref[0, pl.ds(j * ck, ck), :].astype(jnp.float32)
        mk = mask_ref[0, 0, pl.ds(j * ck, ck)]
        s = jax.lax.dot_general(
            q, k_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [Bq, ck]
        valid = _tile_valid(bq, ck, row0, j * ck, causal) & (mk[None, :] > 0)
        s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - m) / l  # [Bq, ck]
        dp = jax.lax.dot_general(
            do, v_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [Bq, ck]
        # masked entries carry no gradient into s (the reference's
        # where() routes their cotangent to the NEG_INF constant);
        # explicit zeroing matters on fully-masked rows where p is
        # uniform, not ~0
        ds = jnp.where(valid, p * (dp - delta) * sm_scale, 0.0)
        return dq_acc + jax.lax.dot_general(
            ds, k_c, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = jax.lax.fori_loop(0, n_chunks, body, jnp.zeros((bq, D), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, m_ref, l_ref, delta_ref, dk_ref, dv_ref,
    *, sm_scale, causal, q_offset, n_chunks, cq,
):
    """dk/dv for one key block. Works in TRANSPOSED orientation
    ([Bk, cq] score tiles) so the per-row stats stream in lane-major
    [1, T] layout — a [T, 1] operand would be lane-padded to [T, 128]
    in VMEM (4 MB per stat at 8k tokens), which blows the budget."""
    bk = k_ref.shape[1]
    D = k_ref.shape[2]
    k = k_ref[0].astype(jnp.float32)  # [Bk, D]
    v = v_ref[0].astype(jnp.float32)
    col0 = pl.program_id(1) * bk
    mk = mask_ref[0, 0, pl.ds(col0, bk)]  # [Bk]

    def body(j, carry):
        dk_acc, dv_acc = carry
        q_c = q_ref[0, pl.ds(j * cq, cq), :].astype(jnp.float32)  # [cq, D]
        do_c = do_ref[0, pl.ds(j * cq, cq), :].astype(jnp.float32)
        m_c = m_ref[0, 0, pl.ds(j * cq, cq)]  # [cq] (lane vector)
        l_c = jnp.maximum(l_ref[0, 0, pl.ds(j * cq, cq)], 1e-30)
        delta_c = delta_ref[0, 0, pl.ds(j * cq, cq)]
        s_t = jax.lax.dot_general(
            k, q_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [Bk, cq]
        rows = col0 + jax.lax.broadcasted_iota(jnp.int32, (bk, cq), 0)  # key idx
        cols = j * cq + q_offset + jax.lax.broadcasted_iota(jnp.int32, (bk, cq), 1)
        valid = (cols >= rows) if causal else jnp.ones((bk, cq), jnp.bool_)
        valid = valid & (mk[:, None] > 0)
        s_t = jnp.where(valid, s_t, NEG_INF)
        p_t = jnp.exp(s_t - m_c[None, :]) / l_c[None, :]  # [Bk, cq]
        dv_new = dv_acc + jax.lax.dot_general(
            p_t, do_c, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [Bk, D]
        dp_t = jax.lax.dot_general(
            v, do_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [Bk, cq]
        ds_t = jnp.where(valid, p_t * (dp_t - delta_c[None, :]) * sm_scale, 0.0)
        dk_new = dk_acc + jax.lax.dot_general(
            ds_t, q_c, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [Bk, D]
        return dk_new, dv_new

    z = jnp.zeros((bk, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, n_chunks, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, key_mask, o, m, l, g, causal, sm_scale, block_q):
    B, H, T, D = q.shape
    S = k.shape[2]
    if key_mask is None:
        key_mask = jnp.ones((B, S), jnp.int32)
    mask3 = key_mask.astype(jnp.int32)[:, None, :]

    qr = q.reshape(B * H, T, D)
    kr = k.reshape(B * H, S, D)
    vr = v.reshape(B * H, S, D)
    dor = g.reshape(B * H, T, D)
    # delta_i = rowsum(dO_i * O_i): tiny elementwise pass, fine in XLA
    delta = jnp.sum(
        dor.astype(jnp.float32) * o.reshape(B * H, T, D).astype(jnp.float32),
        axis=-1, keepdims=True,
    )  # [BH, T, 1]

    bq = _pick_block(T, block_q)
    ck = _pick_block(S, CHUNK)
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, sm_scale=sm_scale, causal=causal, q_offset=S - T,
            n_chunks=S // ck, ck=ck,
        ),
        grid=(B * H, T // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda bh, qi: (bh // H, 0, 0)),
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=_interpret(),
    )(qr, kr, vr, mask3, dor, m, l, delta)

    bk = _pick_block(S, block_q)
    cq = _pick_block(T, CHUNK)
    # lane-major stat views for the dkv kernel (see its docstring)
    m_t = m.reshape(B * H, 1, T)
    l_t = l.reshape(B * H, 1, T)
    delta_t = delta.reshape(B * H, 1, T)
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, sm_scale=sm_scale, causal=causal, q_offset=S - T,
            n_chunks=T // cq, cq=cq,
        ),
        grid=(B * H, S // bk),
        in_specs=[
            pl.BlockSpec((1, T, D), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, 1, S), lambda bh, ki: (bh // H, 0, 0)),
            pl.BlockSpec((1, T, D), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, 1, T), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, 1, T), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, 1, T), lambda bh, ki: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, S, D), v.dtype),
        ],
        interpret=_interpret(),
    )(qr, kr, vr, mask3, dor, m_t, l_t, delta_t)

    return (
        dq.reshape(B, H, T, D),
        dk.reshape(B, H, S, D),
        dv.reshape(B, H, S, D),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention(q, k, v, key_mask, causal=True, sm_scale=None, block_q=256):
    """Fused attention. q/k/v: [B, H, T|S, D]; key_mask: [B, S] (1=real).

    Causality compares PHYSICAL slots with queries right-aligned against
    keys (q_offset = S - T), matching the transformer's slot semantics.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_forward(q, k, v, key_mask, causal, sm_scale, block_q)


def _fwd(q, k, v, key_mask, causal, sm_scale, block_q):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    out, m, l = _flash_forward(
        q, k, v, key_mask, causal, sm_scale, block_q, with_stats=True
    )
    return out, (q, k, v, key_mask, out, m, l)


def _bwd(causal, sm_scale, block_q, res, g):
    q, k, v, key_mask, o, m, l = res
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    dq, dk, dv = _flash_backward(
        q, k, v, key_mask, o, m, l, g, causal, sm_scale, block_q
    )
    return dq, dk, dv, None


flash_attention.defvjp(_fwd, _bwd)
