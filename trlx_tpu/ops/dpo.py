"""DPO numerics: the sigmoid preference loss over policy-vs-frozen-
reference logprob margins (Rafailov et al., arXiv:2305.18290).

DPO is offline preference RL without a reward model or sampling: for
each (prompt, chosen, rejected) pair the implicit reward of a
completion is ``beta * (log pi(y|x) - log pi_ref(y|x))`` and the loss
is binary logistic regression on the reward margin. Both functions are
pure and jittable; ``dpo_loss`` runs unchanged inside the fused-block
``lax.scan`` train path (the scanned epoch machinery is loss-agnostic).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.ops.common import flatten_dict, logprobs_of_labels


def sequence_logprobs(
    logits: jnp.ndarray, input_ids: jnp.ndarray, response_mask: jnp.ndarray
) -> jnp.ndarray:
    """Summed next-token logprob of each row's RESPONSE tokens.

    logits: [batch, seq, vocab]; input_ids / response_mask: [batch,
    seq] with response_mask = 1 exactly on completion tokens (the
    prompt and padding contribute nothing). Position ``t``'s label is
    ``input_ids[t+1]`` — the standard shift."""
    lp = logprobs_of_labels(logits[:, :-1], input_ids[:, 1:])
    return (lp * response_mask[:, 1:].astype(jnp.float32)).sum(axis=-1)


def dpo_loss(
    policy_chosen_logps: jnp.ndarray,
    policy_rejected_logps: jnp.ndarray,
    ref_chosen_logps: jnp.ndarray,
    ref_rejected_logps: jnp.ndarray,
    beta: float,
    label_smoothing: float = 0.0,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Sigmoid DPO objective on per-sequence summed logprobs [batch].

    ``-log sigmoid(beta * margin)`` where ``margin = (pi_c - ref_c) -
    (pi_r - ref_r)``; ``label_smoothing`` is the conservative-DPO mix
    (arXiv:2305.18290 eq. 7 footnote / cDPO): probability the
    preference label is flipped. The reference logps enter
    stop-gradiented — the frozen reference never trains.
    """
    ref_chosen_logps = jax.lax.stop_gradient(ref_chosen_logps)
    ref_rejected_logps = jax.lax.stop_gradient(ref_rejected_logps)
    chosen_rewards = beta * (policy_chosen_logps - ref_chosen_logps)
    rejected_rewards = beta * (policy_rejected_logps - ref_rejected_logps)
    margin = chosen_rewards - rejected_rewards

    loss = (
        -jax.nn.log_sigmoid(margin) * (1.0 - label_smoothing)
        - jax.nn.log_sigmoid(-margin) * label_smoothing
    ).mean()

    stats = dict(
        losses=dict(total_loss=loss),
        dpo=dict(
            accuracy=(margin > 0).astype(jnp.float32).mean(),
            margin=margin.mean(),
            chosen_reward=chosen_rewards.mean(),
            rejected_reward=rejected_rewards.mean(),
            logprob_chosen=policy_chosen_logps.mean(),
            logprob_rejected=policy_rejected_logps.mean(),
        ),
    )
    return loss, flatten_dict(stats)
