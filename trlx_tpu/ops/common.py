"""Shared numeric primitives.

Parity: /root/reference/trlx/utils/modeling.py:185-314 (whiten,
logprobs_of_labels, get_tensor_stats, RunningMoments, flatten_dict) and
/root/reference/trlx/models/modeling_ilql.py:29-46 (topk_mask,
batched_index_select) — re-expressed as pure JAX.

Distribution note: these run inside `jit` over a `Mesh` with batch
sharded along `dp`. GSPMD makes `jnp.mean`/`jnp.sum` global across the
mesh automatically, so the reference's explicit all_reduce paths
(get_global_statistics) need no separate "distributed" branch. An
optional `axis_name` argument covers `shard_map`/`pmap` contexts where
reductions are per-shard.
"""

from __future__ import annotations

from typing import Dict, MutableMapping, Optional, Tuple, Union

import flax.struct
import jax
import jax.numpy as jnp


def masked_mean(xs: jnp.ndarray, mask: Optional[jnp.ndarray], axis=None) -> jnp.ndarray:
    if mask is None:
        return jnp.mean(xs, axis=axis)
    mask = mask.astype(xs.dtype)
    return (xs * mask).sum(axis=axis) / jnp.maximum(mask.sum(axis=axis), 1e-8)


def _global_mean_var(
    xs: jnp.ndarray, axis_name: Optional[str] = None
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Element-count, mean and (biased) variance, reduced over `axis_name`
    if inside shard_map/pmap, else over the (logically global) array."""
    count = jnp.asarray(xs.size, jnp.float32)
    total = xs.sum()
    if axis_name is not None:
        count = jax.lax.psum(count, axis_name)
        total = jax.lax.psum(total, axis_name)
    mean = total / count
    sq = ((xs - mean) ** 2).sum()
    if axis_name is not None:
        sq = jax.lax.psum(sq, axis_name)
    return mean, sq / count, count


def whiten(
    xs: jnp.ndarray,
    shift_mean: bool = True,
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """Normalize to zero mean / unit variance (across the global batch).

    Uses the UNBIASED variance, matching the reference's single-process
    path (`torch.var_mean` default — utils/modeling.py:212), which is
    what its published curves were trained with. (The reference's
    distributed branch divides by N instead — an inconsistency we don't
    reproduce; golden tests pin the single-process numbers.)
    """
    mean, var, count = _global_mean_var(xs, axis_name)
    var = var * count / jnp.maximum(count - 1, 1.0)
    whitened = (xs - mean) * jax.lax.rsqrt(var + 1e-8)
    if not shift_mean:
        whitened = whitened + mean
    return whitened


def logprobs_of_labels(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """log p(label_t) from logits [..., seq, vocab] and labels [..., seq].

    Computed without materializing the full log-softmax gather in fp32 HBM:
    logsumexp is fused by XLA with the label gather.
    """
    labels = labels[..., None]
    picked = jnp.take_along_axis(logits, labels, axis=-1)[..., 0]
    return picked.astype(jnp.float32) - jax.nn.logsumexp(
        logits.astype(jnp.float32), axis=-1
    )


def chunked_logprobs(
    project_fn,
    hidden: jnp.ndarray,
    labels: jnp.ndarray,
    n_chunks: int,
) -> jnp.ndarray:
    """Per-token log p(label) from hidden states, never materializing the
    full [batch, seq, vocab] logits.

    `project_fn(hidden_chunk) -> logits_chunk` is the model's hidden->
    logits projection (models.transformer.logit_projection /
    models.seq2seq.t5_logit_projection — same einsum/dtype contract as
    the in-model `_logits`, so this path is numerically the full-logits
    path up to reduction order). The sequence axis is split into
    `n_chunks` pieces and scanned with `jax.checkpoint`: the backward
    recomputes each chunk's logits, so peak logit residency is
    [batch, ceil(seq/n_chunks), vocab] instead of [batch, seq, vocab] —
    at b8/seq2048/vocab50257 fp32 that's 0.4 GB instead of 3.3 GB, the
    difference between the 1.3B recipe fitting one 16 GB chip or not.

    Returns fp32 logprobs with the shape of `labels`.
    """
    B, T = labels.shape
    ck = -(-T // n_chunks)
    pad = n_chunks * ck - T
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)))
    hs = hidden.reshape(B, n_chunks, ck, hidden.shape[-1]).transpose(1, 0, 2, 3)
    ls = labels.reshape(B, n_chunks, ck).transpose(1, 0, 2)

    def body(carry, xt):
        h, lab = xt
        return carry, logprobs_of_labels(project_fn(h), lab)

    body = jax.checkpoint(body, prevent_cse=False)
    _, lp = jax.lax.scan(body, jnp.zeros((), jnp.float32), (hs, ls))
    lp = lp.transpose(1, 0, 2).reshape(B, n_chunks * ck)
    return lp[:, :T]


def topk_mask(xs: jnp.ndarray, k: int) -> jnp.ndarray:
    """Mask all but the top-k logits to -inf (k >= vocab is a no-op)."""
    if k <= 0 or k >= xs.shape[-1]:
        return xs
    kth = jax.lax.top_k(xs, k)[0][..., -1:]
    return jnp.where(xs < kth, -jnp.inf, xs)


def batched_index_select(x: jnp.ndarray, idxs: jnp.ndarray, dim: int = 1) -> jnp.ndarray:
    """Gather rows of x [batch, seq, hidden] at idxs [batch, n] along `dim`."""
    idxs = jnp.expand_dims(idxs, -1)
    if x.ndim == idxs.ndim:
        idxs = jnp.broadcast_to(idxs, idxs.shape[:-1] + (x.shape[-1],))
        return jnp.take_along_axis(x, idxs, axis=dim)
    return jnp.take_along_axis(x, idxs[..., 0], axis=dim)


def get_tensor_stats(xs: jnp.ndarray, mask: jnp.ndarray, n) -> Dict[str, jnp.ndarray]:
    """mean/min/max/std over masked entries (parity: utils/modeling.py:269-279)."""
    if xs.size == 0:
        zero = jnp.float32(0)
        return dict(mean=zero, min=zero, max=zero, std=zero)
    mask = mask.astype(xs.dtype)
    n = jnp.maximum(n, 1e-8)
    mean = (xs * mask).sum() / n
    return dict(
        mean=mean,
        min=jnp.where(mask > 0, xs, jnp.inf).min(),
        max=jnp.where(mask > 0, xs, -jnp.inf).max(),
        std=jnp.sqrt((((xs - mean) * mask) ** 2).sum() / n),
    )


def flatten_dict(d: Union[dict, MutableMapping], parent_key: str = "", sep: str = "/") -> dict:
    """{"a": {"b": 1}} -> {"a/b": 1} (metric-key parity with the reference)."""
    items = {}
    for k, v in d.items():
        key = f"{parent_key}{sep}{k}" if parent_key else str(k)
        if isinstance(v, MutableMapping):
            items.update(flatten_dict(v, key, sep=sep))
        else:
            items[key] = v
    return items


# ---------------------------------------------------------------------------
# Running moments — functional state (Chan et al. parallel variance), the
# pytree version of reference RunningMoments (utils/modeling.py:282-314).
# ---------------------------------------------------------------------------


@flax.struct.dataclass
class RunningMoments:
    mean: jnp.ndarray
    var: jnp.ndarray
    std: jnp.ndarray
    count: jnp.ndarray


def running_moments_init() -> RunningMoments:
    return RunningMoments(
        mean=jnp.float32(0.0),
        var=jnp.float32(1.0),
        std=jnp.float32(1.0),
        count=jnp.float32(1e-24),
    )


def running_moments_update(
    state: RunningMoments, xs: jnp.ndarray, axis_name: Optional[str] = None
) -> Tuple[RunningMoments, jnp.ndarray, jnp.ndarray]:
    """Fold a batch into the running moments.

    Returns (new_state, batch_mean, batch_std) where batch_std is the
    unbiased standard deviation of `xs` itself.
    """
    xs_mean, xs_var, xs_count = _global_mean_var(xs, axis_name)
    delta = xs_mean - state.mean
    tot_count = state.count + xs_count

    new_sum = xs_var * xs_count
    old_sum = state.var * state.count + delta**2 * state.count * xs_count / tot_count
    tot_sum = old_sum + new_sum

    new_mean = state.mean + delta * xs_count / tot_count
    new_var = tot_sum / tot_count
    new_state = RunningMoments(
        mean=new_mean,
        var=new_var,
        std=jnp.sqrt(new_var * tot_count / jnp.maximum(tot_count - 1, 1e-8)),
        count=tot_count,
    )
    batch_std = jnp.sqrt(xs_var * xs_count / jnp.maximum(xs_count - 1, 1e-8))
    return new_state, xs_mean, batch_std


# ---------------------------------------------------------------------------
# Shared pallas plumbing — every kernel family (ops/flash_attention.py,
# ops/decode_attention.py, the paged decode kernel) makes the same two
# decisions the same way; private per-file copies of these had already
# drifted into three call sites before they were factored here.
# ---------------------------------------------------------------------------


def interpret_mode() -> bool:
    """True when pallas kernels should run interpreted (no Mosaic on
    this backend). CPU-only: TPU/GPU lower for real. Tier-1 runs every
    kernel through this path, which is what makes kernel==reference
    goldens runnable without device time."""
    return jax.default_backend() == "cpu"


def pick_block(n: int, block: int) -> int:
    """Largest power-of-two shrink of `block` that divides `n` (from
    min(block, n) downward). Callers gate `n` on their own alignment
    floors (e.g. 128-divisibility for lane-dim dynamic slices)."""
    b = min(block, n)
    while n % b:
        b //= 2
    return b
