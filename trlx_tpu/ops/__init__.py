"""Pure jittable numerics: losses, advantages, sampling, statistics.

Everything in this package is a pure function of arrays + static
hyperparameters — the TPU-native answer to the reference's mixture of
loss methods on config objects and torch.distributed stat helpers
(/root/reference/trlx/utils/modeling.py:185-314).
"""

from trlx_tpu.ops.common import (
    RunningMoments,
    batched_index_select,
    flatten_dict,
    get_tensor_stats,
    logprobs_of_labels,
    masked_mean,
    running_moments_init,
    running_moments_update,
    topk_mask,
    whiten,
)
from trlx_tpu.ops.ppo import gae_advantages_and_returns, ppo_loss
from trlx_tpu.ops.ilql import ilql_loss

__all__ = [
    "RunningMoments",
    "batched_index_select",
    "flatten_dict",
    "gae_advantages_and_returns",
    "get_tensor_stats",
    "ilql_loss",
    "logprobs_of_labels",
    "masked_mean",
    "ppo_loss",
    "running_moments_init",
    "running_moments_update",
    "topk_mask",
    "whiten",
]
