"""Selective activation-checkpointing policies.

Parity: the reference's NeMo backend exposes activation-checkpointing
granularity (selective / uniform / block) per
/root/reference/configs/nemo_configs/megatron_20b.yaml:76-80, toggled in
/root/reference/trlx/models/modeling_nemo_ppo.py:788-817. On TPU the
same levers are `jax.checkpoint` rematerialization policies applied to
the scanned layer body — the policy decides which intermediates XLA
keeps across the forward->backward boundary and which it recomputes
(or offloads to host memory) instead:

  none          keep everything (no remat; fastest forward, peak memory)
  full          keep only layer boundaries; recompute everything inside
                each block on the backward pass (NeMo "uniform" with one
                block per layer). `save_nothing` is an alias.
  dots_saveable keep matmul outputs, recompute elementwise/norm/softmax
                chains (NeMo "selective" — the flash-attention-friendly
                middle ground: backward skips the matmul re-FLOPs but
                the big activations still never live all-layers-long)
  dots_with_no_batch_dims
                keep only batch-free matmul results (weight-stationary
                contractions); attention score/context matmuls (batched)
                are recomputed. Lower memory than dots_saveable.
  offload       dots_with_no_batch_dims, but offload the saved results
                to pinned host memory instead of keeping them in HBM —
                trades PCIe/DMA bandwidth for HBM at very long context.

Trainers resolve `train.remat_policy` once via `resolve_remat` (so the
falsy/truthy checks threaded through the model code keep working: the
resolved value is `False` or a non-empty policy name) and the three scan
bodies (causal blocks, seq2seq blocks, pipeline stage ticks) wrap
themselves with `wrap_remat`.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

RematArg = Union[bool, str]

_POLICY_NAMES = (
    "none",
    "full",
    "save_nothing",
    "dots_saveable",
    "dots_with_no_batch_dims",
    "offload",
    "save_attn",
)


def resolve_remat(policy: RematArg) -> RematArg:
    """Validate a config `remat_policy` and normalize it for threading:
    returns False for "none" (so `if remat:` checks stay correct) and
    the policy name otherwise. Bools pass through (legacy call sites)."""
    if isinstance(policy, bool):
        return policy
    if policy not in _POLICY_NAMES:
        raise ValueError(
            f"remat_policy={policy!r} not in {_POLICY_NAMES}"
        )
    return False if policy == "none" else policy


def checkpoint_policy(remat: RematArg) -> Optional[Callable]:
    """The jax.checkpoint `policy` for a resolved remat arg (None means
    the default nothing-saveable, i.e. full recompute)."""
    p = jax.checkpoint_policies
    if isinstance(remat, bool) or remat in ("full", "save_nothing"):
        return None
    return {
        "dots_saveable": p.dots_saveable,
        "dots_with_no_batch_dims": p.dots_with_no_batch_dims_saveable,
        "offload": p.offload_dot_with_no_batch_dims("device", "pinned_host"),
        # full recompute EXCEPT the pallas attention kernel's residuals
        # (ops/flash_attention.py names its out + softmax stats): the
        # backward's remat re-runs projections and elementwise chains but
        # never the online-softmax sweep itself. ~1 extra [B,T,E]-sized
        # save per layer vs "full"; no effect on the XLA attention path
        # (nothing is named there).
        "save_attn": p.save_only_these_names(
            "flash_out", "flash_m", "flash_l"
        ),
    }[remat]


def wrap_remat(fn: Callable, remat: RematArg) -> Callable:
    """Apply jax.checkpoint with the resolved policy ("none"/False: fn
    unchanged). prevent_cse=False is safe under scan/while (the layer
    bodies are always inside one) and lets XLA fuse freely."""
    if not remat or remat == "none":
        return fn
    return jax.checkpoint(fn, prevent_cse=False, policy=checkpoint_policy(remat))
