"""PPO numerics: GAE and the clipped surrogate objective.

Parity: /root/reference/trlx/models/modeling_ppo.py:136-238 — identical
math and stat keys; the reference's reversed Python loop over timesteps
becomes a `lax.scan` (single fused kernel, no per-step dispatch).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.ops.common import flatten_dict, get_tensor_stats, whiten


def gae_advantages_and_returns(
    values: jnp.ndarray,
    rewards: jnp.ndarray,
    gamma: float,
    lam: float,
    use_whitening: bool = True,
    axis_name: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Generalized advantage estimation over the response window.

    values, rewards: [batch, response_len] (rewards already include the
    per-token KL penalty). Returns (advantages, returns); advantages are
    whitened across the global batch and gradient-stopped.
    """
    resp_len = values.shape[1]
    next_values = jnp.concatenate(
        [values[:, 1:], jnp.zeros_like(values[:, :1])], axis=1
    )
    deltas = rewards + gamma * next_values - values  # [batch, T]

    def step(lastgaelam, delta_t):
        adv = delta_t + gamma * lam * lastgaelam
        return adv, adv

    # scan over time, reversed: carry is A_{t+1}
    _, advs = jax.lax.scan(
        step, jnp.zeros_like(deltas[:, 0]), deltas.T, reverse=True
    )
    advantages = advs.T  # [batch, T]
    returns = advantages + values
    if use_whitening:
        advantages = whiten(advantages, axis_name=axis_name)
    return jax.lax.stop_gradient(advantages), returns


def ppo_loss(
    logprobs: jnp.ndarray,
    values: jnp.ndarray,
    old_logprobs: jnp.ndarray,
    old_values: jnp.ndarray,
    advantages: jnp.ndarray,
    returns: jnp.ndarray,
    mask: jnp.ndarray,
    cliprange: float,
    cliprange_value: float,
    vf_coef: float,
    is_weight: Optional[jnp.ndarray] = None,
    norm_n: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Clipped-ratio policy loss + clipped value loss, masked over real
    response tokens. All shapes [batch, response_len].

    ``is_weight`` is the experience transport's staleness correction
    (``exp.staleness.mode: clip``): a per-token CLIPPED importance
    weight rho = clip(pi_proximal/pi_behavior, 1±c) computed at chunk
    admission (IMPACT, arXiv:1912.00167 — ``old_logprobs`` are then the
    proximal recompute, and the behavior mismatch rides this factor).
    It multiplies only the policy surrogate; stop-gradiented, so it
    scales each token's objective without entering the ratio's
    gradient. None (the default and every fresh chunk) is exactly
    weight 1.

    ``norm_n`` overrides the mask-count normalizer (default: this
    call's own ``mask.sum()``). The memory doctor's microbatch split
    passes ``full_mask_total / num_mb`` so the mean over accumulated
    microbatches reproduces the unsplit step's ``sum/N_total`` EXACTLY
    even with ragged response masks — each microbatch normalizing by
    its own count would weight microbatches by 1/n_k instead."""
    mask = mask.astype(jnp.float32)
    n = jnp.maximum(mask.sum() if norm_n is None else norm_n, 1e-8)

    values_clipped = jnp.clip(
        values, old_values - cliprange_value, old_values + cliprange_value
    )
    vf_loss1 = (values - returns) ** 2
    vf_loss2 = (values_clipped - returns) ** 2
    vf_loss = 0.5 * (jnp.maximum(vf_loss1, vf_loss2) * mask).sum() / n
    vf_clipfrac = ((vf_loss2 > vf_loss1).astype(jnp.float32) * mask).sum() / n

    log_ratio = (logprobs - old_logprobs) * mask
    ratio = jnp.exp(log_ratio)
    # k3 estimator, http://joschu.net/blog/kl-approx.html
    approx_kl = jax.lax.stop_gradient(jnp.mean((ratio - 1) - log_ratio))

    w = 1.0 if is_weight is None else jax.lax.stop_gradient(
        is_weight.astype(jnp.float32)
    )
    pg_loss1 = -advantages * ratio * w
    pg_loss2 = -advantages * jnp.clip(ratio, 1.0 - cliprange, 1.0 + cliprange) * w
    pg_loss = (jnp.maximum(pg_loss1, pg_loss2) * mask).sum() / n
    pg_clipfrac = ((pg_loss2 > pg_loss1).astype(jnp.float32) * mask).sum() / n

    loss = pg_loss + vf_coef * vf_loss

    stats = dict(
        losses=dict(total_loss=loss, policy_loss=pg_loss, value_loss=vf_loss),
        values=dict(
            get_tensor_stats(values, mask, n),
            values_error=(((values - returns) * mask) ** 2).sum() / n,
            values_mape_error=(jnp.abs(values - returns) * mask
                               / jnp.abs(returns * mask + 1e-2)).sum() / n,
            clipfrac=vf_clipfrac,
        ),
        old_values=get_tensor_stats(old_values, mask, n),
        returns=get_tensor_stats(returns, mask, n),
        policy=dict(approx_kl=approx_kl, clipfrac=pg_clipfrac),
        ratio=(ratio * mask).sum() / n,
        padding_percentage=1.0 - n / mask.size,
    )
    return loss, flatten_dict(stats)
