"""GRPO numerics: group-relative advantages and the critic-free
clipped surrogate (DeepSeekMath, arXiv:2402.03300).

GRPO keeps PPO's clipped importance-ratio objective (ops/ppo.py) but
replaces the learned critic with a Monte-Carlo baseline computed from a
GROUP of N samples per prompt: each sample's advantage is the z-score
of its reward within its group. No value head, no value loss, no GAE —
the whole value column of PPO's train-phase state disappears. The KL
regularizer moves from the reward (PPO's per-token penalty) into the
LOSS, estimated per token against the frozen reference with the same
k3 estimator ops/ppo.py uses (http://joschu.net/blog/kl-approx.html).

Both functions are pure and jittable: `grpo_loss` runs inside the same
fused-block `lax.scan` train path as `ppo_loss` (train.fused_inner_loop
— the scanned epoch machinery is loss-agnostic), and
`group_relative_advantages` is shape-polymorphic so the trainer can
call it on host numpy or device arrays.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.ops.common import flatten_dict, get_tensor_stats

# degenerate-group guard: a group whose rewards are (numerically) all
# equal carries no preference signal — its advantages are defined as
# exactly zero rather than 0/eps noise (or NaN at eps=0)
GROUP_STD_FLOOR = 1e-6


def group_relative_advantages(
    rewards: jnp.ndarray, group_size: int
) -> jnp.ndarray:
    """Per-group reward z-scores: ``(r - mean_g) / (std_g + 1e-6)``.

    ``rewards``: [batch] scalar rewards where rows ``i*group_size ...
    (i+1)*group_size - 1`` are the N samples of prompt ``i`` (the GRPO
    trainer tiles each pulled prompt ``group_size`` times, so group
    members are consecutive). ``batch`` must be a multiple of
    ``group_size``. ``std_g`` is the population (1/N) standard
    deviation. A degenerate group (std <= 1e-6 — all members scored
    equal) gets advantage exactly 0 for every member, not NaN.
    """
    if rewards.shape[0] % group_size:
        raise ValueError(
            f"rewards batch {rewards.shape[0]} is not a multiple of "
            f"group_size {group_size}"
        )
    r = rewards.astype(jnp.float32).reshape(-1, group_size)
    centered = r - r.mean(axis=1, keepdims=True)
    std = jnp.sqrt((centered**2).mean(axis=1, keepdims=True))
    adv = jnp.where(
        std > GROUP_STD_FLOOR,
        centered / (std + GROUP_STD_FLOOR),
        jnp.zeros_like(centered),
    )
    return adv.reshape(rewards.shape)


def grpo_loss(
    logprobs: jnp.ndarray,
    old_logprobs: jnp.ndarray,
    ref_logprobs: jnp.ndarray,
    advantages: jnp.ndarray,
    mask: jnp.ndarray,
    cliprange: float,
    kl_coef: float,
    is_weight: Optional[jnp.ndarray] = None,
    norm_n: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Clipped-ratio policy loss with a sequence-level advantage and an
    in-loss KL regularizer against the frozen reference.

    logprobs / old_logprobs / ref_logprobs / mask: [batch, resp_len];
    advantages: [batch] (one group-relative z-score per SAMPLE,
    broadcast over its response tokens). ``old_logprobs`` are the
    behavior logprobs stored at collection; ``ref_logprobs`` the frozen
    reference's, fixed for the life of the rollout batch.

    The KL term is the k3 estimator of KL(pi || pi_ref) per token,
    differentiated through ``logprobs`` (parity with the GRPO paper's
    unbiased low-variance form): ``exp(ref - lp) - 1 - (ref - lp)``.

    ``is_weight`` is the experience transport's staleness correction
    (``exp.staleness.mode: clip``) — identical contract to
    ops/ppo.py: a stop-gradiented per-token clipped importance weight
    multiplying only the policy surrogate; None = weight 1.

    ``norm_n`` overrides the mask-count normalizer (same contract as
    ops/ppo.py: the memory doctor's microbatch split passes
    full_total/num_mb so the accumulated mean equals the unsplit
    step's normalization exactly with ragged masks).
    """
    mask = mask.astype(jnp.float32)
    n = jnp.maximum(mask.sum() if norm_n is None else norm_n, 1e-8)
    adv = jax.lax.stop_gradient(advantages.astype(jnp.float32))[:, None]

    log_ratio = (logprobs - old_logprobs) * mask
    ratio = jnp.exp(log_ratio)
    approx_kl = jax.lax.stop_gradient(jnp.mean((ratio - 1) - log_ratio))

    w = 1.0 if is_weight is None else jax.lax.stop_gradient(
        is_weight.astype(jnp.float32)
    )
    pg_loss1 = -adv * ratio * w
    pg_loss2 = -adv * jnp.clip(ratio, 1.0 - cliprange, 1.0 + cliprange) * w
    pg_loss = (jnp.maximum(pg_loss1, pg_loss2) * mask).sum() / n
    pg_clipfrac = ((pg_loss2 > pg_loss1).astype(jnp.float32) * mask).sum() / n

    # k3 KL(pi||ref) >= 0 per token; masked token-mean
    ref_log_ratio = (ref_logprobs - logprobs) * mask
    kl = (jnp.exp(ref_log_ratio) - 1 - ref_log_ratio) * mask
    kl_loss = kl.sum() / n

    loss = pg_loss + kl_coef * kl_loss

    stats = dict(
        losses=dict(total_loss=loss, policy_loss=pg_loss, kl_loss=kl_loss),
        advantages=get_tensor_stats(
            jnp.broadcast_to(adv, mask.shape), mask, n
        ),
        policy=dict(approx_kl=approx_kl, clipfrac=pg_clipfrac, ref_kl=kl_loss),
        ratio=(ratio * mask).sum() / n,
        padding_percentage=1.0 - n / mask.size,
    )
    return loss, flatten_dict(stats)
