"""Pallas fused decode attention: contiguous int8 caches AND paged pools.

Two kernel families live here behind the two decode-cache layouts:

  * ``decode_attention_int8`` — the original contiguous-layout kernel
    (stacked [L, B, Hkv, S, D] int8 caches, one (batch, kv-head) grid
    cell streaming its S-width rows; design notes below).
  * ``paged_attention_pallas`` (selected through
    ``paged_attention_step(impl="pallas")``) — the paged-pool kernel:
    the slot→page table becomes the block index map, so K/V pages load
    from the pool's HBM layout without the gathered S-width cache ever
    materializing, per-row int8 scales fold into the score/prob tiles
    in-kernel, GQA attends grouped, and the same kernel serves the T=1
    decode step and the T=draft_k speculative verify forward.


Decode at large batch×seq is bound on the full-cache read every step
(1.61 GB int8 at 1.3B b8 seq2048). Driving that read through XLA ops
costs three extra O(S·D) materializations per layer (measured via
profile trace, 2026-07-31: the int8→bf16 convert un-fuses from the AV
dot, the QK dot runs as a kLoop fusion at ~60% of the read roofline,
and a per-token V dequant costs a 0.56 ms/step probs multiply). This
kernel does the whole per-layer attention step in one pass: each
(batch, kv-head) grid cell streams its int8 K/V rows into VMEM once,
computes fp32 scores with the per-slot K scales folded in, runs an
online softmax, and applies the per-channel V scales to the tiny
[rep, D] output — nothing S-sized ever goes back to HBM.

Layer indexing: the decode loop scans over layers carrying the stacked
[L, B, Hkv, S, D] buffers; the layer index arrives as a SCALAR-PREFETCH
argument so the kernel reads its layer's blocks straight out of the
full carried buffer — slicing the layer out in XLA first would
materialize a 33 MB copy per layer per step, which is the exact
traffic the kernel exists to avoid.

Scale layout (chosen so both dequants commute out of the reductions —
see transformer.Attention's int8 branch for the measured alternative):
  k_scale [L, B, Hkv, 1, S] fp32 — multiplies scores per key slot
  v_scale [L, B, Hkv, 1, D] fp32 — multiplies the output per channel

The reference has no decode-attention kernel at all: its rollout
generation is HF `model.generate` over full-precision torch caches
(/root/reference/trlx/trainer/accelerate_ppo_trainer.py:285).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from trlx_tpu.ops.common import interpret_mode as _interpret

NEG_INF = -1e30
CHUNK = 512  # fp32 score tile per in-kernel step: [rep, CHUNK]


def paged_attention_step(
    q,  # [B, T, H, D] queries (rope already applied), T >= 1
    k_new,  # [B, T, Hkv, D] this step's keys (pre-quantization)
    v_new,  # [B, T, Hkv, D] this step's values
    pools: Dict[str, jnp.ndarray],  # pk/pv [L, NP, PS, Hkv, D] (+ scales)
    layer_ix,  # scalar int32: which layer's pages to touch
    page_table,  # [B, MP] int32 slot -> page indirection
    slot_pos,  # [B] int32: logical slot of the FIRST incoming token
    attn_bias,  # [B, 1, T, S] additive fp32 (S = MP * PS)
    sm_scale: float,
    lane_valid: Optional[jnp.ndarray] = None,  # [B] bool; False -> trash write
    contiguous: bool = False,
    impl: str = "xla",
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """One layer's attention over a paged KV cache: write the T incoming
    tokens' K/V into their pages, then attend every query against the
    slot's full logical sequence, with the per-row quant scales folded
    into the score / prob vectors so int8 K/V are never dequantized at
    S width (the dense int8 path's folded-scale recipe, generalized to
    per-row indirection and per-row positions).

    Serves both the single-token decode step (T=1) and the speculative
    verify forward (T=draft_k): causality among the T incoming tokens is
    carried by `attn_bias` (slot-index comparison), so the same code is
    exact for both. Returns (out [B, T, H, D], updated pools).

    ``impl`` selects the attend half (``gen_engine.paged_attention_impl``):

      xla     gather the slot's logical [B, S] view of the pool, then
              plain-XLA attention over it. GQA attends GROUPED (one
              einsum per kv-head group) — kv is never repeat-
              materialized at S width.
      pallas  :func:`paged_attention_pallas` — the page table becomes
              the kernel's block index map, so K/V pages stream from
              the pool's HBM layout into VMEM without the gathered
              S-width cache ever existing.

    The write half (a [B, T] scatter) is tiny and shared by both. The
    ``contiguous`` layout always takes the XLA path: its gather
    collapses to a slice+reshape that XLA fuses into the attention
    reads like a dense cache, which is the exact behavior the
    ``paged=false`` benches attribute against — a kernel there would
    change the baseline, not beat it.
    """
    from trlx_tpu.ops.paged_kv import (
        gather_layer,
        quantize_rows,
        scatter_layer,
        write_positions,
    )

    if impl not in ("xla", "pallas"):
        raise ValueError(f"paged attention impl must be xla/pallas, got {impl!r}")
    B, T, H, D = q.shape
    Hkv = k_new.shape[2]
    PS = pools["pk"].shape[2]
    quant = "pk_scale" in pools
    positions = slot_pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    pids, offs = write_positions(page_table, positions, PS, lane_valid)

    new_pools = dict(pools)
    if quant:
        kq, ks = quantize_rows(k_new)  # [B, T, Hkv] scales
        vq, vs = quantize_rows(v_new)
        new_pools["pk"] = scatter_layer(pools["pk"], layer_ix, pids, offs, kq)
        new_pools["pv"] = scatter_layer(pools["pv"], layer_ix, pids, offs, vq)
        new_pools["pk_scale"] = scatter_layer(
            pools["pk_scale"], layer_ix, pids, offs, ks
        )
        new_pools["pv_scale"] = scatter_layer(
            pools["pv_scale"], layer_ix, pids, offs, vs
        )
    else:
        new_pools["pk"] = scatter_layer(pools["pk"], layer_ix, pids, offs, k_new)
        new_pools["pv"] = scatter_layer(pools["pv"], layer_ix, pids, offs, v_new)

    # read AFTER the write (update-carry-first, like the dense cache
    # branch): each query sees every token up to and including itself;
    # older/unwritten/stale slots are excluded by attn_bias
    if impl == "pallas" and not contiguous:
        out = paged_attention_pallas(
            q, new_pools, layer_ix, page_table, attn_bias, sm_scale
        )
        return out, new_pools

    k_all = gather_layer(new_pools["pk"], layer_ix, page_table, contiguous)
    v_all = gather_layer(new_pools["pv"], layer_ix, page_table, contiguous)
    ks_all = vs_all = None
    if quant:
        ks_all = gather_layer(
            new_pools["pk_scale"], layer_ix, page_table, contiguous
        )  # [B, S, Hkv]
        vs_all = gather_layer(
            new_pools["pv_scale"], layer_ix, page_table, contiguous
        )
    if H == Hkv:
        scores = jnp.einsum(
            "bthd,bshd->bhts", q, k_all.astype(q.dtype),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if quant:
            # per-row K scale rides the score tensor; per-row V scale
            # rides the prob tensor — both commute out of the reductions
            scores = scores * ks_all.transpose(0, 2, 1)[:, :, None, :]
            probs = jax.nn.softmax(scores + attn_bias, axis=-1)
            probs = (probs * vs_all.transpose(0, 2, 1)[:, :, None, :]).astype(
                q.dtype
            )
        else:
            probs = jax.nn.softmax(scores + attn_bias, axis=-1).astype(q.dtype)
        out = jnp.einsum("bhts,bshd->bthd", probs, v_all.astype(q.dtype))
        return out.astype(q.dtype), new_pools

    # GQA: attend GROUPED — the einsum batches over kv heads with the
    # rep query heads of each group as a free axis, so kv (and scales)
    # are read at Hkv width instead of being jnp.repeat-materialized to
    # H x S per step (the rep-fold memory the old fallback paid)
    rep = H // Hkv
    qg = q.reshape(B, T, Hkv, rep, D)
    scores = jnp.einsum(
        "btgrd,bsgd->bgrts", qg, k_all.astype(q.dtype),
        preferred_element_type=jnp.float32,
    ) * sm_scale  # [B, Hkv, rep, T, S]
    bias_g = attn_bias[:, :, None]  # [B, 1, 1, T, S] broadcasts over (g, r)
    if quant:
        scores = scores * ks_all.transpose(0, 2, 1)[:, :, None, None, :]
        probs = jax.nn.softmax(scores + bias_g, axis=-1)
        probs = (
            probs * vs_all.transpose(0, 2, 1)[:, :, None, None, :]
        ).astype(q.dtype)
    else:
        probs = jax.nn.softmax(scores + bias_g, axis=-1).astype(q.dtype)
    out = jnp.einsum("bgrts,bsgd->btgrd", probs, v_all.astype(q.dtype))
    return out.reshape(B, T, H, D).astype(q.dtype), new_pools


def _paged_kernel(
    lx_ref,  # scalar prefetch: [1] layer index (consumed by index maps)
    pt_ref,  # scalar prefetch: [B*MP] flattened page table (index maps)
    q_ref,  # [1, Hkv, rep*T, D] — group-blocked queries, rows t*rep+r
    k_ref,  # [1, 1, PS, Hkv, D] — ONE page, routed here by pt_ref
    v_ref,  # [1, 1, PS, Hkv, D]
    *rest,  # (+ks_ref/vs_ref when quant) b_ref, o_ref, o/m/l scratch
    sm_scale,
    rep,
    quant,
):
    """One (batch row, page) grid cell: score the row's queries against
    this page's keys for every kv head, fold the page's per-row int8
    scales in, and fold the tile into the online-softmax accumulators.
    Pages are the INNERMOST grid axis, so the accumulators live in VMEM
    scratch across the row's page sweep and the output block flushes
    once at the last page."""
    if quant:
        ks_ref, vs_ref, b_ref, o_ref, o_scratch, m_scratch, l_scratch = rest
    else:
        b_ref, o_ref, o_scratch, m_scratch, l_scratch = rest
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    Hkv = q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        o_scratch[...] = jnp.zeros_like(o_scratch)
        m_scratch[...] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[...] = jnp.zeros_like(l_scratch)

    # additive bias strip [T, PS] carries ALL masking (per-row lengths,
    # slot-index causality, null pages); rows are t*rep+r so the rep
    # group members of token t share bias[t]
    bias_rows = jnp.repeat(b_ref[0, 0], rep, axis=0)  # [rep*T, PS]
    for h in range(Hkv):  # static unroll: per-kv-head 2D dots
        qh = q_ref[0, h].astype(jnp.float32)  # [rep*T, D]
        kh = k_ref[0, 0, :, h, :].astype(jnp.float32)  # [PS, D]
        s = jax.lax.dot_general(
            qh, kh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [rep*T, PS]
        if quant:
            # per-slot K dequant folded into the score tile
            s = s * ks_ref[0, 0, :, h][None, :]
        s = s + bias_rows
        m_run = m_scratch[h]  # [rep*T, 1]
        l_run = l_scratch[h]
        m_new = jnp.maximum(m_run, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_run - m_new)
        p = jnp.exp(s - m_new)
        l_scratch[h] = l_run * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_scratch[h] = m_new
        if quant:
            # per-slot V dequant rides the prob tile (commutes out of
            # the over-S dot, exactly like the gather path)
            p = p * vs_ref[0, 0, :, h][None, :]
        vh = v_ref[0, 0, :, h, :].astype(jnp.float32)
        o_scratch[h] = o_scratch[h] * corr + jax.lax.dot_general(
            p, vh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nj - 1)
    def _flush():
        o_ref[0] = (
            o_scratch[...] / jnp.maximum(l_scratch[...], 1e-30)
        ).astype(o_ref.dtype)


def paged_attention_pallas(
    q,  # [B, T, H, D]
    pools: Dict[str, jnp.ndarray],  # POST-write pools (pk/pv [+ scales])
    layer_ix,  # scalar int32
    page_table,  # [B, MP] int32
    attn_bias,  # [B, 1, T, S] additive fp32
    sm_scale: float,
):
    """Pallas paged-attention: the page table IS the block index map.

    Grid (B, MP) with pages innermost: cell (b, j) DMAs page
    ``page_table[b, j]`` of this layer straight out of the pool's
    [L, NP, PS, Hkv, D] HBM layout (both table and layer index arrive
    as scalar-prefetch arguments, so the routing happens before the
    kernel body runs) and folds it into per-(kv-head) online-softmax
    accumulators held in VMEM scratch across the row's page sweep. The
    gathered [B, S, Hkv, D] logical cache — the XLA path's three extra
    O(S·D) materializations per layer — never exists anywhere. GQA
    attends grouped: queries arrive group-blocked ([Hkv, rep*T, D] per
    row), so each page is read ONCE per row and shared by its group's
    rep query heads. Null pages (table entry 0) are loaded but fully
    masked by the bias strip, matching the gather path's null-page
    semantics slot for slot.

    One kernel serves the T=1 decode step and the T=draft_k speculative
    verify forward — causality among the T incoming tokens rides the
    same slot-index ``attn_bias`` the XLA path uses.
    """
    B, T, H, D = q.shape
    PS, Hkv = pools["pk"].shape[2], pools["pk"].shape[3]
    MP = page_table.shape[1]
    quant = "pk_scale" in pools
    if H % Hkv:
        raise ValueError(f"n_head={H} not a multiple of n_kv_head={Hkv}")
    rep = H // Hkv
    if not _interpret() and PS % 128:
        raise ValueError(
            f"gen_engine.paged_attention_impl=pallas needs page_size a "
            f"multiple of 128 on TPU (got {PS}): the per-page bias/score "
            "tiles are lane-blocked at 128 — use page_size=128 or "
            "paged_attention_impl=xla"
        )
    # group-blocked queries: row t*rep + r of group g is query head
    # g*rep + r at token t (consecutive rep heads share a kv head)
    qg = q.reshape(B, T, Hkv, rep, D).transpose(0, 2, 1, 3, 4).reshape(
        B, Hkv, rep * T, D
    )

    def page_ix(b, j, lx, pt):
        return (lx[0], pt[b * MP + j], 0, 0, 0)

    def scale_ix(b, j, lx, pt):
        return (lx[0], pt[b * MP + j], 0, 0)

    in_specs = [
        pl.BlockSpec((1, Hkv, rep * T, D), lambda b, j, lx, pt: (b, 0, 0, 0)),
        pl.BlockSpec((1, 1, PS, Hkv, D), page_ix),
        pl.BlockSpec((1, 1, PS, Hkv, D), page_ix),
    ]
    operands = [qg, pools["pk"], pools["pv"]]
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1, PS, Hkv), scale_ix),
            pl.BlockSpec((1, 1, PS, Hkv), scale_ix),
        ]
        operands += [pools["pk_scale"], pools["pv_scale"]]
    in_specs.append(
        pl.BlockSpec((1, 1, T, PS), lambda b, j, lx, pt: (b, 0, 0, j))
    )
    operands.append(attn_bias.astype(jnp.float32))

    kernel = functools.partial(
        _paged_kernel, sm_scale=sm_scale, rep=rep, quant=quant
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, MP),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, Hkv, rep * T, D), lambda b, j, lx, pt: (b, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((Hkv, rep * T, D), jnp.float32),
                pltpu.VMEM((Hkv, rep * T, 1), jnp.float32),
                pltpu.VMEM((Hkv, rep * T, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, rep * T, D), q.dtype),
        interpret=_interpret(),
    )(
        jnp.reshape(layer_ix, (1,)).astype(jnp.int32),
        page_table.reshape(-1).astype(jnp.int32),
        *operands,
    )
    return out.reshape(B, Hkv, T, rep, D).transpose(0, 2, 1, 3, 4).reshape(
        B, T, H, D
    )


def _decode_kernel(
    lx_ref,  # scalar prefetch: [1] layer index (consumed by index maps)
    q_ref,  # [1, 1, rep, D]
    k_ref,  # [1, 1, 1, S, D] int8
    v_ref,  # [1, 1, 1, S, D] int8
    ks_ref,  # [1, 1, 1, 1, S] f32
    vs_ref,  # [1, 1, 1, D] f32 (per-layer slice; no layer axis)
    mask_ref,  # [1, 1, S] int32
    o_ref,  # [1, 1, rep, D]
    *,
    sm_scale,
    n_chunks,
    ck,
):
    rep, D = q_ref.shape[2], q_ref.shape[3]
    q = q_ref[0, 0].astype(jnp.float32)  # [rep, D]

    def body(j, carry):
        o_acc, m_run, l_run = carry
        k_c = k_ref[0, 0, 0, pl.ds(j * ck, ck), :].astype(jnp.float32)
        ks_c = ks_ref[0, 0, 0, 0, pl.ds(j * ck, ck)]  # [ck]
        mk = mask_ref[0, 0, pl.ds(j * ck, ck)]  # [ck]
        s = jax.lax.dot_general(
            q, k_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [rep, ck]
        # per-slot K dequant + softmax scale fold into the score tile
        s = s * (ks_c * sm_scale)[None, :]
        s = jnp.where(mk[None, :] > 0, s, NEG_INF)

        m_new = jnp.maximum(m_run, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_run - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_run * corr + jnp.sum(p, axis=-1, keepdims=True)
        v_c = v_ref[0, 0, 0, pl.ds(j * ck, ck), :].astype(jnp.float32)
        o_new = o_acc * corr + jax.lax.dot_general(
            p, v_c, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((rep, D), jnp.float32)
    m0 = jnp.full((rep, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((rep, 1), jnp.float32)
    o, _, l = jax.lax.fori_loop(0, n_chunks, body, (o0, m0, l0))
    # per-channel V dequant commutes out of the over-S dot: one [rep, D]
    # multiply after normalization
    o = (o / jnp.maximum(l, 1e-30)) * vs_ref[0, 0]
    o_ref[0, 0] = o.astype(o_ref.dtype)


def decode_attention_int8(
    q,  # [B, H, D] (rope already applied)
    ck,  # [L, B, Hkv, S, D] int8 — full stacked cache
    cv,  # [L, B, Hkv, S, D] int8
    k_scale,  # [L, B, Hkv, 1, S] f32
    v_scale,  # [B, Hkv, 1, D] f32 — this layer's slice (frozen scales
    #           ride the layer scan's xs, so no layer axis here)
    key_mask,  # [B, S] int32 — 1 for attendable slots (incl. this token)
    layer_ix,  # scalar int32: which layer's blocks to read
    sm_scale: float,
):
    """One decode step's attention for ONE layer of the stacked cache.

    Returns [B, H, D] in q.dtype. Requires S % 128 == 0 (Mosaic lane
    granularity for the in-kernel chunk loads; generate() rounds real
    rollout caches to 128 slots) — callers fall back to the XLA path
    otherwise (transformer.Attention gates on the same condition).
    """
    L, B, Hkv, S, D = ck.shape
    H = q.shape[1]
    if H % Hkv:
        raise ValueError(f"n_head={H} not a multiple of n_kv_head={Hkv}")
    rep = H // Hkv
    # largest power-of-two chunk <= CHUNK that divides S: callers are
    # gated on S % 128 == 0, so this bottoms out at >= 128 (lane-aligned
    # for the in-kernel dynamic loads) instead of rejecting e.g. S=640
    from trlx_tpu.ops.common import pick_block

    ckk = pick_block(S, CHUNK)
    if ckk < 128:
        raise ValueError(f"cache length {S} must be a multiple of 128")

    # consecutive rep query heads share a kv head (head h -> group
    # h // rep), so [B, H, D] -> [B, Hkv, rep, D] groups them per cell
    qr = q.reshape(B, Hkv, rep, D)
    grid = (B, Hkv)

    kernel = functools.partial(
        _decode_kernel, sm_scale=sm_scale, n_chunks=S // ckk, ck=ckk
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, rep, D), lambda b, h, lx: (b, h, 0, 0)),
                pl.BlockSpec(
                    (1, 1, 1, S, D), lambda b, h, lx: (lx[0], b, h, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, 1, S, D), lambda b, h, lx: (lx[0], b, h, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, 1, 1, S), lambda b, h, lx: (lx[0], b, h, 0, 0)
                ),
                pl.BlockSpec((1, 1, 1, D), lambda b, h, lx: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, S), lambda b, h, lx: (b, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, rep, D), lambda b, h, lx: (b, h, 0, 0)
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, rep, D), q.dtype),
        interpret=_interpret(),
    )(
        jnp.reshape(layer_ix, (1,)).astype(jnp.int32),
        qr,
        ck,
        cv,
        k_scale,
        v_scale,
        key_mask.astype(jnp.int32)[:, None, :],
    )
    return out.reshape(B, H, D)
