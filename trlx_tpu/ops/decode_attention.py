"""Pallas fused single-token decode attention over an int8 KV cache.

Decode at large batch×seq is bound on the full-cache read every step
(1.61 GB int8 at 1.3B b8 seq2048). Driving that read through XLA ops
costs three extra O(S·D) materializations per layer (measured via
profile trace, 2026-07-31: the int8→bf16 convert un-fuses from the AV
dot, the QK dot runs as a kLoop fusion at ~60% of the read roofline,
and a per-token V dequant costs a 0.56 ms/step probs multiply). This
kernel does the whole per-layer attention step in one pass: each
(batch, kv-head) grid cell streams its int8 K/V rows into VMEM once,
computes fp32 scores with the per-slot K scales folded in, runs an
online softmax, and applies the per-channel V scales to the tiny
[rep, D] output — nothing S-sized ever goes back to HBM.

Layer indexing: the decode loop scans over layers carrying the stacked
[L, B, Hkv, S, D] buffers; the layer index arrives as a SCALAR-PREFETCH
argument so the kernel reads its layer's blocks straight out of the
full carried buffer — slicing the layer out in XLA first would
materialize a 33 MB copy per layer per step, which is the exact
traffic the kernel exists to avoid.

Scale layout (chosen so both dequants commute out of the reductions —
see transformer.Attention's int8 branch for the measured alternative):
  k_scale [L, B, Hkv, 1, S] fp32 — multiplies scores per key slot
  v_scale [L, B, Hkv, 1, D] fp32 — multiplies the output per channel

The reference has no decode-attention kernel at all: its rollout
generation is HF `model.generate` over full-precision torch caches
(/root/reference/trlx/trainer/accelerate_ppo_trainer.py:285).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
CHUNK = 512  # fp32 score tile per in-kernel step: [rep, CHUNK]


def paged_attention_step(
    q,  # [B, T, H, D] queries (rope already applied), T >= 1
    k_new,  # [B, T, Hkv, D] this step's keys (pre-quantization)
    v_new,  # [B, T, Hkv, D] this step's values
    pools: Dict[str, jnp.ndarray],  # pk/pv [L, NP, PS, Hkv, D] (+ scales)
    layer_ix,  # scalar int32: which layer's pages to touch
    page_table,  # [B, MP] int32 slot -> page indirection
    slot_pos,  # [B] int32: logical slot of the FIRST incoming token
    attn_bias,  # [B, 1, T, S] additive fp32 (S = MP * PS)
    sm_scale: float,
    lane_valid: Optional[jnp.ndarray] = None,  # [B] bool; False -> trash write
    contiguous: bool = False,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """One layer's attention over a paged KV cache: write the T incoming
    tokens' K/V into their pages, then attend every query against the
    slot's full logical sequence (gathered pages), with the per-row
    quant scales folded into the score / prob vectors so int8 K/V are
    never dequantized at S width (the dense int8 path's folded-scale
    recipe, generalized to per-row indirection and per-row positions).

    Serves both the single-token decode step (T=1) and the speculative
    verify forward (T=draft_k): causality among the T incoming tokens is
    carried by `attn_bias` (slot-index comparison), so the same code is
    exact for both. Returns (out [B, T, H, D], updated pools).
    """
    from trlx_tpu.ops.paged_kv import (
        gather_layer,
        quantize_rows,
        scatter_layer,
        write_positions,
    )

    B, T, H, D = q.shape
    Hkv = k_new.shape[2]
    PS = pools["pk"].shape[2]
    quant = "pk_scale" in pools
    positions = slot_pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    pids, offs = write_positions(page_table, positions, PS, lane_valid)

    new_pools = dict(pools)
    if quant:
        kq, ks = quantize_rows(k_new)  # [B, T, Hkv] scales
        vq, vs = quantize_rows(v_new)
        new_pools["pk"] = scatter_layer(pools["pk"], layer_ix, pids, offs, kq)
        new_pools["pv"] = scatter_layer(pools["pv"], layer_ix, pids, offs, vq)
        new_pools["pk_scale"] = scatter_layer(
            pools["pk_scale"], layer_ix, pids, offs, ks
        )
        new_pools["pv_scale"] = scatter_layer(
            pools["pv_scale"], layer_ix, pids, offs, vs
        )
    else:
        new_pools["pk"] = scatter_layer(pools["pk"], layer_ix, pids, offs, k_new)
        new_pools["pv"] = scatter_layer(pools["pv"], layer_ix, pids, offs, v_new)

    # read AFTER the write (update-carry-first, like the dense cache
    # branch): each query sees every token up to and including itself;
    # older/unwritten/stale slots are excluded by attn_bias
    k_all = gather_layer(new_pools["pk"], layer_ix, page_table, contiguous)
    v_all = gather_layer(new_pools["pv"], layer_ix, page_table, contiguous)
    if H != Hkv:
        rep = H // Hkv
        k_all = jnp.repeat(k_all, rep, axis=2)
        v_all = jnp.repeat(v_all, rep, axis=2)
    scores = jnp.einsum(
        "bthd,bshd->bhts", q, k_all.astype(q.dtype),
        preferred_element_type=jnp.float32,
    ) * sm_scale
    if quant:
        ks_all = gather_layer(
            new_pools["pk_scale"], layer_ix, page_table, contiguous
        )  # [B, S, Hkv]
        vs_all = gather_layer(
            new_pools["pv_scale"], layer_ix, page_table, contiguous
        )
        if H != Hkv:
            rep = H // Hkv
            ks_all = jnp.repeat(ks_all, rep, axis=2)
            vs_all = jnp.repeat(vs_all, rep, axis=2)
        # per-row K scale rides the score tensor; per-row V scale rides
        # the prob tensor — both commute out of the attention reductions
        scores = scores * ks_all.transpose(0, 2, 1)[:, :, None, :]
        probs = jax.nn.softmax(scores + attn_bias, axis=-1)
        probs = (probs * vs_all.transpose(0, 2, 1)[:, :, None, :]).astype(
            q.dtype
        )
    else:
        probs = jax.nn.softmax(scores + attn_bias, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhts,bshd->bthd", probs, v_all.astype(q.dtype))
    return out.astype(q.dtype), new_pools


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _decode_kernel(
    lx_ref,  # scalar prefetch: [1] layer index (consumed by index maps)
    q_ref,  # [1, 1, rep, D]
    k_ref,  # [1, 1, 1, S, D] int8
    v_ref,  # [1, 1, 1, S, D] int8
    ks_ref,  # [1, 1, 1, 1, S] f32
    vs_ref,  # [1, 1, 1, D] f32 (per-layer slice; no layer axis)
    mask_ref,  # [1, 1, S] int32
    o_ref,  # [1, 1, rep, D]
    *,
    sm_scale,
    n_chunks,
    ck,
):
    rep, D = q_ref.shape[2], q_ref.shape[3]
    q = q_ref[0, 0].astype(jnp.float32)  # [rep, D]

    def body(j, carry):
        o_acc, m_run, l_run = carry
        k_c = k_ref[0, 0, 0, pl.ds(j * ck, ck), :].astype(jnp.float32)
        ks_c = ks_ref[0, 0, 0, 0, pl.ds(j * ck, ck)]  # [ck]
        mk = mask_ref[0, 0, pl.ds(j * ck, ck)]  # [ck]
        s = jax.lax.dot_general(
            q, k_c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [rep, ck]
        # per-slot K dequant + softmax scale fold into the score tile
        s = s * (ks_c * sm_scale)[None, :]
        s = jnp.where(mk[None, :] > 0, s, NEG_INF)

        m_new = jnp.maximum(m_run, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_run - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_run * corr + jnp.sum(p, axis=-1, keepdims=True)
        v_c = v_ref[0, 0, 0, pl.ds(j * ck, ck), :].astype(jnp.float32)
        o_new = o_acc * corr + jax.lax.dot_general(
            p, v_c, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((rep, D), jnp.float32)
    m0 = jnp.full((rep, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((rep, 1), jnp.float32)
    o, _, l = jax.lax.fori_loop(0, n_chunks, body, (o0, m0, l0))
    # per-channel V dequant commutes out of the over-S dot: one [rep, D]
    # multiply after normalization
    o = (o / jnp.maximum(l, 1e-30)) * vs_ref[0, 0]
    o_ref[0, 0] = o.astype(o_ref.dtype)


def decode_attention_int8(
    q,  # [B, H, D] (rope already applied)
    ck,  # [L, B, Hkv, S, D] int8 — full stacked cache
    cv,  # [L, B, Hkv, S, D] int8
    k_scale,  # [L, B, Hkv, 1, S] f32
    v_scale,  # [B, Hkv, 1, D] f32 — this layer's slice (frozen scales
    #           ride the layer scan's xs, so no layer axis here)
    key_mask,  # [B, S] int32 — 1 for attendable slots (incl. this token)
    layer_ix,  # scalar int32: which layer's blocks to read
    sm_scale: float,
):
    """One decode step's attention for ONE layer of the stacked cache.

    Returns [B, H, D] in q.dtype. Requires S % 128 == 0 (Mosaic lane
    granularity for the in-kernel chunk loads; generate() rounds real
    rollout caches to 128 slots) — callers fall back to the XLA path
    otherwise (transformer.Attention gates on the same condition).
    """
    L, B, Hkv, S, D = ck.shape
    H = q.shape[1]
    if H % Hkv:
        raise ValueError(f"n_head={H} not a multiple of n_kv_head={Hkv}")
    rep = H // Hkv
    # largest power-of-two chunk <= CHUNK that divides S: callers are
    # gated on S % 128 == 0, so this bottoms out at >= 128 (lane-aligned
    # for the in-kernel dynamic loads) instead of rejecting e.g. S=640
    ckk = min(CHUNK, S)
    while S % ckk:
        ckk //= 2
    if ckk < 128:
        raise ValueError(f"cache length {S} must be a multiple of 128")

    # consecutive rep query heads share a kv head (head h -> group
    # h // rep), so [B, H, D] -> [B, Hkv, rep, D] groups them per cell
    qr = q.reshape(B, Hkv, rep, D)
    grid = (B, Hkv)

    kernel = functools.partial(
        _decode_kernel, sm_scale=sm_scale, n_chunks=S // ckk, ck=ckk
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, rep, D), lambda b, h, lx: (b, h, 0, 0)),
                pl.BlockSpec(
                    (1, 1, 1, S, D), lambda b, h, lx: (lx[0], b, h, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, 1, S, D), lambda b, h, lx: (lx[0], b, h, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, 1, 1, S), lambda b, h, lx: (lx[0], b, h, 0, 0)
                ),
                pl.BlockSpec((1, 1, 1, D), lambda b, h, lx: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, S), lambda b, h, lx: (b, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, rep, D), lambda b, h, lx: (b, h, 0, 0)
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, rep, D), q.dtype),
        interpret=_interpret(),
    )(
        jnp.reshape(layer_ix, (1,)).astype(jnp.int32),
        qr,
        ck,
        cv,
        k_scale,
        v_scale,
        key_mask.astype(jnp.int32)[:, None, :],
    )
    return out.reshape(B, H, D)
