"""ILQL numerics: TD Q-loss, expectile value loss, CQL and AWAC terms,
plus the advantage-shaped sampling perturbation.

Parity: /root/reference/trlx/models/modeling_ilql.py:94-166 (loss) and
:325-412 / modeling_nemo_ilql.py:723-735 (beta*(minQ - V) logit shaping).
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.ops.common import (
    batched_index_select,
    flatten_dict,
    get_tensor_stats,
    topk_mask,
)


def ilql_loss(
    logits: jnp.ndarray,  # [batch, n_actions, vocab] (already action-selected)
    qs: Sequence[jnp.ndarray],  # each [batch, n_actions, vocab]
    target_qs: Sequence[jnp.ndarray],
    vs: jnp.ndarray,  # [batch, n_states, 1]; n_states = n_actions + 1
    labels,  # ILQLBatch (actions from input_ids) or seq2seq batch
    tau: float,
    gamma: float,
    cql_scale: float,
    awac_scale: float,
    beta: float,
    two_qs: bool,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    from trlx_tpu.data import ILQLBatch

    dones = labels.dones.astype(jnp.float32)
    terminal_mask = dones[:, :-1]  # [batch, n_actions]
    n_nonterminal = jnp.maximum(terminal_mask.sum(), 1.0)

    if isinstance(labels, ILQLBatch):
        shifted = labels.input_ids[:, 1:]
        actions = jnp.take_along_axis(shifted, labels.actions_ixs, axis=1)
    else:
        actions = labels.decoder_input_ids[:, 1:]
    actions = actions[..., None]  # [batch, n_actions, 1]
    bsize, nactions, dsize = logits.shape

    def pick(q):
        return jnp.take_along_axis(q, actions, axis=-1)[..., 0]

    Q = [pick(q) for q in qs]
    targetQ = jax.lax.stop_gradient(
        jnp.minimum(*[pick(q) for q in target_qs]) if two_qs else pick(target_qs[0])
    )

    V = vs[:, :-1, 0]  # values of current states
    Vnext = vs[:, 1:, 0] * dones[:, 1:]
    Q_target = labels.rewards + gamma * jax.lax.stop_gradient(Vnext)

    loss_q = sum(
        (((Qi - Q_target) * terminal_mask) ** 2).sum() / n_nonterminal for Qi in Q
    )

    # expectile regression of V toward min target-Q
    vdiff2 = (targetQ - V) ** 2
    loss_v = (
        jnp.where(targetQ >= V, tau * vdiff2, (1 - tau) * vdiff2) * terminal_mask
    ).sum() / n_nonterminal

    def masked_xent(scores):
        logp = jax.nn.log_softmax(scores.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, actions, axis=-1)[..., 0]
        return nll  # [batch, n_actions]

    loss_cql = sum(
        (masked_xent(q) * terminal_mask).sum() / n_nonterminal for q in qs
    )

    cross_entropy = masked_xent(logits)
    awac_weight = jax.lax.stop_gradient(jnp.exp(beta * (targetQ - V)))
    loss_awac = (cross_entropy * awac_weight * terminal_mask).sum() / n_nonterminal

    loss = loss_q + loss_v + cql_scale * loss_cql + awac_scale * loss_awac

    stats = dict(
        losses=dict(
            loss=loss, loss_q=loss_q, loss_v=loss_v,
            loss_cql=loss_cql, loss_awac=loss_awac,
        ),
        values=get_tensor_stats(V, terminal_mask, n_nonterminal),
        qvalues={
            str(ix): get_tensor_stats(Q[ix], terminal_mask, n_nonterminal)
            for ix in range(len(Q))
        },
        awac_weight=get_tensor_stats(awac_weight, terminal_mask, n_nonterminal),
    )
    return loss, flatten_dict(stats)


def ilql_shape_logits(
    logits: jnp.ndarray,  # [batch, vocab] last-position logits
    qs: Sequence[jnp.ndarray],  # each [batch, vocab]
    vs: jnp.ndarray,  # [batch, 1]
    beta: float,
    top_k: int = 0,
) -> jnp.ndarray:
    """Perturb sampling logits by the advantage: pi_beta + beta*(minQ - V).

    This is ILQL's inference-time policy improvement (parity:
    modeling_ilql.py:365-374); a pure function usable inside the jitted
    decode loop.
    """
    min_q = qs[0] if len(qs) == 1 else jnp.minimum(*qs)
    adv = min_q - vs
    shaped = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1) + beta * adv
    if top_k:
        shaped = topk_mask(shaped, top_k)
    return shaped
