"""Paged KV cache: fixed-size pages + slot→page indirection tables.

The dense decode cache allocates `batch × (prompt + max_new_tokens)`
slots per row for the whole rollout, so every row pays max-length KV
even when its response ends after 10 tokens — and a finished row's
memory cannot be reused until the whole batch finishes. Pages fix both:
the cache is a pool of fixed-size pages ([L, n_pages, page_size, Hkv, D]
int8 by default) plus a per-slot page table, so

  * a decode slot allocates response pages LAZILY as its sequence grows
    (a row that stops at 10 tokens never touches its other pages),
  * a refilled slot (continuous batching, models/gen_engine.py) returns
    its pages to a free stack and the next prompt reuses them,
  * the pool is sized to expected LIVE tokens, not slots × max length,
  * pages can carry REFERENCE COUNTS (init_refcounts /
    release_refcounted) so the serving tier's shared system-prompt
    prefixes and pinned multi-turn sessions keep their pages alive
    across requests and engine calls; free-at-finish is the
    refcount-zero degenerate case and stays the training-path default.

Quantization is symmetric per-(slot, kv-head) over the D axis for BOTH
K and V (the same `_quantize_kv` formula the dense int8 cache applies
to K): a per-row scale multiplies the score vector (K) or the prob
vector (V), so both dequants commute out of the attention reductions
and nothing S-sized is ever dequantized to HBM. This differs from the
dense path's frozen per-channel V scales deliberately — per-row V
scales need no saturation headroom and no freeze point, which matters
when slots are refilled with fresh prompts mid-rollout.

Vocabulary: a *slot* is a decode lane (row of the step batch); a *page*
holds `page_size` consecutive logical positions of one slot's sequence.
Page 0 is RESERVED as the null/trash page: unassigned page-table
entries point at it, and masked lanes write into it, so it must never
be allocated (init_alloc never hands it out) and is never marked
attendable. The "contiguous" layout (page_table[b, j] == 1 + b*MP + j,
never rebuilt) degenerates to a dense per-slot cache — the gather
becomes a reshape — and exists so the engine can attribute the paging
indirection's cost/benefit separately from continuous batching
(bench.py decode section).

All ops here are plain XLA (gathers/scatters). The attend half has two
implementations behind `paged_attention_step` (ops/decode_attention.py):
the XLA gather path (grouped-GQA einsum over the logical [B, S] view)
and the pallas paged kernel (`gen_engine.paged_attention_impl: pallas`),
which uses the page table as its block index map so the gathered
S-width view never materializes.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Array = jnp.ndarray


def pages_per_slot(prompt_len: int, max_new: int, page_size: int) -> int:
    """Logical pages a slot can touch: ceil((P + N) / page_size)."""
    return -(-(prompt_len + max_new) // page_size)


def init_pool(
    n_layer: int,
    n_pages: int,
    page_size: int,
    n_kv_head: int,
    head_dim: int,
    quant: Optional[str],
    dtype,
) -> Dict[str, Array]:
    """Allocate the page pool. Keys: pk/pv (+ pk_scale/pv_scale when
    quant == "int8"). Page 0 is the reserved null page."""
    shape = (n_layer, n_pages, page_size, n_kv_head, head_dim)
    if quant == "int8":
        pool = {
            "pk": jnp.zeros(shape, jnp.int8),
            "pv": jnp.zeros(shape, jnp.int8),
            "pk_scale": jnp.zeros(shape[:4], jnp.float32),
            "pv_scale": jnp.zeros(shape[:4], jnp.float32),
        }
    elif quant in (None, "none"):
        pool = {"pk": jnp.zeros(shape, dtype), "pv": jnp.zeros(shape, dtype)}
    else:
        raise ValueError(f"paged KV quant must be None or 'int8', got {quant!r}")
    return pool


def init_alloc(n_pages: int) -> Tuple[Array, Array]:
    """Free stack over pages 1..n_pages-1 (page 0 reserved null).

    Returns (free, ntop): free[:ntop] are free page ids, popped from the
    TOP (highest index) so allocation order is deterministic."""
    free = jnp.concatenate(
        [jnp.arange(1, n_pages, dtype=jnp.int32), jnp.zeros((1,), jnp.int32)]
    )
    return free, jnp.int32(n_pages - 1)


def push_free(
    free: Array, ntop: Array, pages: Array, is_real: Array
) -> Tuple[Array, Array]:
    """Return `pages[is_real]` to the stack (vectorized, fixed shape).

    `pages` [M] int32, `is_real` [M] bool; entries with is_real=False
    (or page id 0) are dropped. Order among returned pages follows the
    input order."""
    is_real = is_real & (pages > 0)
    order = jnp.cumsum(is_real.astype(jnp.int32)) - 1
    dst = jnp.where(is_real, ntop + order, free.shape[0])  # OOB -> dropped
    free = free.at[dst].set(pages, mode="drop")
    return free, ntop + is_real.sum(dtype=jnp.int32)


def pop_pages(
    free: Array, ntop: Array, want: Array
) -> Tuple[Array, Array, Array]:
    """Pop one page per wanting lane, vectorized at fixed shape.

    `want` [M] bool: lane m wants one page. Lanes are served in input
    order from the top of the stack; a lane beyond the available count
    gets page 0 (null) — callers treat that as allocation failure.
    Returns (page_ids [M], free, ntop)."""
    want = want.astype(jnp.int32)
    order = jnp.cumsum(want) - 1  # 0-based rank among wanting lanes
    have = order < ntop
    src = jnp.where((want > 0) & have, ntop - 1 - order, free.shape[0] - 1)
    # free[free.shape[0]-1] is a zero sentinel kept by init_alloc
    ids = free[src] * ((want > 0) & have)
    taken = ((want > 0) & have).sum(dtype=jnp.int32)
    return ids.astype(jnp.int32), free, ntop - taken


def init_refcounts(n_pages: int) -> Array:
    """Per-page reference counts, all zero. The serving tier
    (trlx_tpu/serve/) is the count authority: before an engine call it
    sets ``refcnt[p] = 1 + (#queue rows mapping p)`` for every page a
    cached prefix/session entry holds, so in-call releases can only
    ever decrement a shared page down to the cache's own hold — never
    onto the free stack. Engine-allocated (unshared) pages stay at 0
    and free exactly like the refcount-free path."""
    return jnp.zeros((n_pages,), jnp.int32)


def release_refcounted(
    free: Array, ntop: Array, refcnt: Array, pages: Array, is_real: Array
) -> Tuple[Array, Array, Array]:
    """Refcount-aware page release: decrement each released page's
    count once; pages at (or already below) zero after the decrement
    return to the free stack in input order, exactly like
    :func:`push_free`.

    ``pages`` [M] int32 with duplicates allowed for SHARED pages only
    (two lanes sharing a prefix finishing in the same event): the
    caller's invariant — count >= 1 + (#rows mapping the page) at call
    entry — guarantees a duplicated page stays positive and is never
    pushed twice. An unshared page (count 0) appears at most once by
    allocator construction, so the single push cannot double-free.
    Returns (free, ntop, refcnt)."""
    is_real = is_real & (pages > 0)
    dec = is_real.astype(jnp.int32)
    # scatter-add the decrements (dup-safe); non-real entries route to
    # the reserved null page 0 with a zero decrement
    safe = jnp.where(is_real, pages, 0)
    refcnt = refcnt.at[safe].add(-dec)
    freed = is_real & (refcnt[pages] <= 0)
    free, ntop = push_free(free, ntop, pages, freed)
    return free, ntop, jnp.maximum(refcnt, 0)


def quantize_rows(x: Array) -> Tuple[Array, Array]:
    """Symmetric per-row int8 over the trailing D axis: (int8, f32 scale
    shaped x.shape[:-1]). Zero rows get scale 0 and dequantize to 0 —
    the same contract as transformer._quantize_kv."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    s = amax / 127.0
    q = jnp.round(
        x.astype(jnp.float32) / jnp.maximum(s, 1e-12)[..., None]
    ).astype(jnp.int8)
    return q, s


def write_positions(
    page_table: Array,  # [B, MP] int32
    positions: Array,  # [B, T] int32 logical slot positions
    page_size: int,
    lane_valid: Optional[Array] = None,  # [B] bool; invalid -> null page
) -> Tuple[Array, Array]:
    """(page_ids [B, T], offsets [B, T]) for scattering tokens at
    `positions` of each slot. Invalid lanes are routed to page 0 (the
    null page), so masked writes land in trash instead of corrupting a
    live slot."""
    MP = page_table.shape[1]
    pix = jnp.clip(positions // page_size, 0, MP - 1)
    pids = jnp.take_along_axis(page_table, pix, axis=1)
    offs = positions % page_size
    if lane_valid is not None:
        pids = jnp.where(lane_valid[:, None], pids, 0)
    return pids.astype(jnp.int32), offs.astype(jnp.int32)


def scatter_layer(
    pool_leaf: Array,  # [L, NP, PS, ...] (values or scales)
    layer_ix: Array,  # scalar int32
    pids: Array,  # [B, T]
    offs: Array,  # [B, T]
    values: Array,  # [B, T, ...]
) -> Array:
    """Scatter one layer's new tokens into the pool, in place on a
    scan-carried buffer."""
    return pool_leaf.at[layer_ix, pids, offs].set(
        values.astype(pool_leaf.dtype)
    )


def scatter_prefill(
    pool_leaf: Array,  # [L, NP, PS, ...]
    pids: Array,  # [R, P]
    offs: Array,  # [R, P]
    values: Array,  # [Lv, R, P, ...]
    layer_ixs: Optional[Array] = None,  # [Lv] pool layer slots
) -> Array:
    """Scatter a whole prefilled prompt block (all layers at once).

    ``layer_ixs`` routes ``values``' layers onto specific pool layer
    slots (gen_engine's spec-decode trunk sharing scatters the DRAFT's
    branch layers into the extension slots past the policy stack);
    None = identity (values span the whole leaf)."""
    if layer_ixs is not None:
        return pool_leaf.at[
            layer_ixs[:, None, None], pids[None, :, :], offs[None, :, :]
        ].set(values.astype(pool_leaf.dtype))
    return pool_leaf.at[:, pids, offs].set(values.astype(pool_leaf.dtype))


def gather_layer(
    pool_leaf: Array,  # [L, NP, PS, ...]
    layer_ix: Array,  # scalar int32
    page_table: Array,  # [B, MP]
    contiguous: bool = False,
) -> Array:
    """This layer's logical [B, MP*PS, ...] view of the pool.

    `contiguous=True` asserts page_table[b, j] == 1 + b*MP + j (the
    engine's unpaged layout): the gather collapses to a slice+reshape,
    which XLA fuses into the attention reads like a dense cache."""
    B, MP = page_table.shape
    layer = jax.lax.dynamic_index_in_dim(pool_leaf, layer_ix, 0, keepdims=False)
    PS = layer.shape[1]
    if contiguous:
        block = jax.lax.dynamic_slice_in_dim(layer, 1, B * MP, axis=0)
        return block.reshape((B, MP * PS) + layer.shape[2:])
    return jnp.take(layer, page_table, axis=0).reshape(
        (B, MP * PS) + layer.shape[2:]
    )
