"""Trace-purity and host-sync-zone checkers (rules ``trace-purity``,
``sync-zone``).

Trace purity: a function handed to ``jit``/``pjit``/``scan``/
``while_loop``/``fori_loop``/``cond``/``switch``/``shard_map``/
``checkpoint`` executes at TRACE time, once — a ``print`` inside it
fires on compilation and never again, ``time.time()`` bakes the
compile-time clock into the graph as a constant, ``np.random`` draws a
single constant sample, and mutating Python state from inside the trace
desynchronizes host bookkeeping from what the compiled graph actually
does on re-execution. All of these are bugs that type-check, run, and
quietly produce wrong numbers.

Host-sync zones: modules that claim "host-side, no device syncs" (the
obs/ flight recorder and the watchdog's beat paths — plus any module
whose docstring makes the claim) must never block the host on the
device: ``.item()``, ``block_until_ready``, ``np.asarray`` on device
arrays, ``jax.device_get``, and module-scope jax imports are all
forbidden there. ``float()``/``bool()`` are flagged only when applied
directly to a jnp/jax call result — host-scalar coercion like
``float(v)`` over dict values is the zones' bread and butter and stays
legal (the narrowing is documented in docs/static_analysis.md).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from trlx_tpu.analysis.common import Finding, Module, dotted, resolve

# tracing entry points: {canonical name: positions of traced fn args}
# (None = first positional arg); decorator forms handled separately
TRACED_ARG_POSITIONS = {
    "jax.jit": (0,),
    "jax.pjit": (0,),
    "jax.experimental.pjit.pjit": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
    "jax.lax.scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": (1,),  # list of branches
    "jax.experimental.shard_map.shard_map": (0,),
    "jax.vmap": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
}
_TRACE_TAILS = {name.split(".")[-1]: pos for name, pos in TRACED_ARG_POSITIONS.items()}

PARTIAL_FNS = {"functools.partial", "partial"}

# modules that get the sync-zone rule by path; a module whose docstring
# claims "no device sync" opts itself in too
DEFAULT_ZONES = ("trlx_tpu/obs/", "trlx_tpu/utils/watchdog.py")
_ZONE_CLAIM = "no device sync"

IMPURE_CALLS = {
    "print": "print() fires once at trace time, never on execution",
    "input": "input() blocks tracing",
    "open": "file I/O at trace time happens once, not per step",
    "time.time": "the compile-time clock becomes a baked-in constant",
    "time.perf_counter": "the compile-time clock becomes a baked-in constant",
    "time.monotonic": "the compile-time clock becomes a baked-in constant",
    "time.process_time": "the compile-time clock becomes a baked-in constant",
    "time.sleep": "sleeping at trace time delays compilation, not steps",
    "datetime.datetime.now": "the compile-time clock becomes a constant",
    "datetime.datetime.utcnow": "the compile-time clock becomes a constant",
}
IMPURE_PREFIXES = {
    "numpy.random.": "np.random draws ONE constant sample at trace time "
                     "— use jax.random with a threaded key",
    "random.": "the random module draws ONE constant sample at trace "
               "time — use jax.random with a threaded key",
}
SYNC_ATTR_CALLS = {
    "item": ".item() blocks the host on the device",
    "block_until_ready": "block_until_ready() is a host-device sync",
    "copy_to_host_async": "host copies do not belong here",
}
SYNC_CALLS = {
    "numpy.asarray": "np.asarray on a device array downloads it",
    "numpy.array": "np.array on a device array downloads it",
    "jax.device_get": "device_get downloads device buffers",
    "jax.block_until_ready": "a host-device sync",
}

# deliberately NOT including "update": optax's pure
# `tx.update(grads, state)` is ubiquitous inside traced steps and a
# dict.update on closed-over state is caught in review far more easily
# than hundreds of pragmas would be maintained (docs/static_analysis.md)
MUTATING_METHODS = {
    "append", "extend", "insert", "remove", "pop", "clear",
    "add", "setdefault", "popitem", "write", "writelines", "discard",
}

# pallas kernels mutate output/scratch Refs by construction — that IS
# the programming model, not trace-time Python mutation
_REF_ROOT_SUFFIXES = ("_ref", "_scratch")


def _resolve_traced_positions(module: Module, fn_node) -> Optional[Sequence[int]]:
    """Arg positions traced by this callee, or None when not a tracer."""
    if not isinstance(fn_node, (ast.Name, ast.Attribute)):
        return None
    canon = resolve(module, fn_node)
    if canon in TRACED_ARG_POSITIONS:
        return TRACED_ARG_POSITIONS[canon]
    tail = (dotted(fn_node) or "").split(".")[-1]
    # jax.* aliasing is common (from jax.lax import scan; lax.scan);
    # match by tail only when the chain plausibly comes from jax
    if tail in _TRACE_TAILS:
        raw = dotted(fn_node) or ""
        if raw == tail or raw.split(".")[0] in (
            "jax", "lax", "jnp", "pjit", "nn"
        ):
            return _TRACE_TAILS[tail]
    return None


class _FnIndex(ast.NodeVisitor):
    """All function-ish nodes, by name, plus parent links for
    traced-region propagation."""

    def __init__(self):
        self.by_name: Dict[str, List[ast.AST]] = {}
        self.functions: List[ast.AST] = []

    def visit_FunctionDef(self, node):
        self.by_name.setdefault(node.name, []).append(node)
        self.functions.append(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self.functions.append(node)
        self.generic_visit(node)


def _is_traced_decorator(module: Module, dec) -> bool:
    if isinstance(dec, (ast.Name, ast.Attribute)):
        return _resolve_traced_positions(module, dec) is not None
    if isinstance(dec, ast.Call):
        fn = dec.func
        if isinstance(fn, (ast.Name, ast.Attribute)):
            if resolve(module, fn) in PARTIAL_FNS and dec.args:
                inner = dec.args[0]
                return isinstance(inner, (ast.Name, ast.Attribute)) and (
                    _resolve_traced_positions(module, inner) is not None
                )
            return _resolve_traced_positions(module, fn) is not None
    return False


def find_traced_functions(module: Module) -> Set[ast.AST]:
    """Function/Lambda nodes whose bodies execute under a trace."""
    index = _FnIndex()
    index.visit(module.tree)
    traced: Set[ast.AST] = set()

    def mark(node):
        if isinstance(node, ast.Lambda):
            traced.add(node)
        elif isinstance(node, (ast.Name, ast.Attribute)):
            name = node.id if isinstance(node, ast.Name) else node.attr
            for fdef in index.by_name.get(name, []):
                traced.add(fdef)

    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_traced_decorator(module, d) for d in node.decorator_list):
                traced.add(node)
        if isinstance(node, ast.Call):
            positions = _resolve_traced_positions(module, node.func)
            if positions is None:
                continue
            for pos in positions:
                if pos >= len(node.args):
                    continue
                arg = node.args[pos]
                if isinstance(arg, (ast.List, ast.Tuple)):  # lax.switch
                    for el in arg.elts:
                        mark(el)
                else:
                    mark(arg)

    # everything nested inside a traced function is traced too
    for fn in list(traced):
        for sub in ast.walk(fn):
            if sub is not fn and isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                traced.add(sub)
    return traced


def _local_names(fn, include_params: bool = True) -> Set[str]:
    """Names bound inside the function. With ``include_params=False``
    only names *assigned* in the body count: objects a traced function
    constructs itself are trace-local bookkeeping, but mutating state
    reached THROUGH a parameter (``self.x = ...``, ``carry[k] = v``,
    ``history.append(...)``) escapes the trace — the parameter object
    outlives it — and is exactly the runs-once-at-trace-time bug."""
    names: Set[str] = set()
    args = fn.args
    if include_params:
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        ):
            names.add(a.arg)
        if args.vararg:
            names.add(args.vararg.arg)
        if args.kwarg:
            names.add(args.kwarg.arg)
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Name) and isinstance(
            sub.ctx, (ast.Store, ast.Del)
        ):
            names.add(sub.id)
        elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(sub.name)
        elif isinstance(sub, ast.comprehension):
            for el in ast.walk(sub.target):
                if isinstance(el, ast.Name):
                    names.add(el.id)
    return names


def _root_name(node) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def check_traced_purity(module: Module) -> List[Finding]:
    findings: List[Finding] = []
    traced = find_traced_functions(module)
    seen_lines: Set[int] = set()

    def add(node, msg):
        if node.lineno in seen_lines:
            return
        seen_lines.add(node.lineno)
        findings.append(Finding(
            "trace-purity", module.path, node.lineno, msg,
            snippet=module.line_at(node.lineno),
        ))

    for fn in traced:
        fname = getattr(fn, "name", "<lambda>")
        # params are NOT mutation-safe: `self.x = ...` or
        # `carry.append(...)` in a traced method mutates state that
        # outlives the trace (a param rebound in the body first
        # becomes an assigned local and is exempt again)
        local = _local_names(fn, include_params=False)
        for node in ast.walk(fn):
            # skip nested traced fns: they are walked separately, and
            # duplicates are folded by seen_lines anyway
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                add(node, (
                    f"traced function `{fname}` rebinds "
                    f"{'/'.join(node.names)} via "
                    f"{'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                    " — trace-time mutation of Python state runs once, "
                    "not per step"
                ))
            elif isinstance(node, ast.Call):
                canon = resolve(module, node.func) or ""
                raw = dotted(node.func) or ""
                if canon in IMPURE_CALLS or raw in IMPURE_CALLS:
                    why = IMPURE_CALLS.get(canon) or IMPURE_CALLS[raw]
                    add(node, f"traced function `{fname}` calls "
                              f"`{raw or canon}`: {why}")
                    continue
                for prefix, why in IMPURE_PREFIXES.items():
                    if canon.startswith(prefix):
                        add(node, f"traced function `{fname}` calls "
                                  f"`{raw}`: {why}")
                        break
                else:
                    if canon in SYNC_CALLS:
                        add(node, f"traced function `{fname}` calls "
                                  f"`{raw}`: {SYNC_CALLS[canon]} — a "
                                  "tracer here fails at trace time or "
                                  "constant-folds silently")
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in SYNC_ATTR_CALLS
                        and not node.args
                    ):
                        add(node, (
                            f"traced function `{fname}` calls "
                            f"`.{node.func.attr}()`: "
                            f"{SYNC_ATTR_CALLS[node.func.attr]}"
                        ))
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in MUTATING_METHODS
                        and isinstance(node.func.value, (ast.Name, ast.Attribute))
                    ):
                        root = _root_name(node.func.value)
                        if root is not None and root not in local:
                            add(node, (
                                f"traced function `{fname}` mutates "
                                f"closed-over state via `{raw}(...)` — "
                                "the mutation happens once at trace "
                                "time, not per executed step"
                            ))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        root = _root_name(tgt)
                        if (
                            root is not None
                            and root not in local
                            and not root.endswith(_REF_ROOT_SUFFIXES)
                        ):
                            add(node, (
                                f"traced function `{fname}` assigns to "
                                f"`{dotted(tgt) or root + '[...]'}` — "
                                "mutating external Python state from "
                                "inside a trace runs once at trace "
                                "time, not per step"
                            ))
    return findings


def _module_claims_zone(module: Module) -> bool:
    doc = ast.get_docstring(module.tree) or ""
    return _ZONE_CLAIM in doc.lower().replace("syncs", "sync")


def check_sync_zone(
    module: Module, zones: Sequence[str] = DEFAULT_ZONES
) -> List[Finding]:
    """Device-sync constructs in a host-side-only module."""
    in_zone = any(
        module.path.startswith(z) or module.path == z.rstrip("/")
        for z in zones
    ) or _module_claims_zone(module)
    if not in_zone:
        return []

    findings: List[Finding] = []

    def add(node, msg):
        findings.append(Finding(
            "sync-zone", module.path, node.lineno,
            msg + " — this module claims 'host-side, no device syncs'",
            snippet=module.line_at(node.lineno),
        ))

    # module-scope jax imports (zones claim jax-free at module scope;
    # lazy function-scope imports stay legal)
    for stmt in module.tree.body:
        if isinstance(stmt, ast.Import):
            for a in stmt.names:
                if a.name == "jax" or a.name.startswith("jax."):
                    add(stmt, f"module-scope `import {a.name}`")
        elif isinstance(stmt, ast.ImportFrom):
            if stmt.module and (
                stmt.module == "jax" or stmt.module.startswith("jax.")
            ):
                add(stmt, f"module-scope `from {stmt.module} import ...`")

    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = resolve(module, node.func) or ""
        raw = dotted(node.func) or ""
        if canon in SYNC_CALLS:
            add(node, f"`{raw}`: {SYNC_CALLS[canon]}")
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in SYNC_ATTR_CALLS
            and not node.args
        ):
            add(node, f"`.{node.func.attr}()`: "
                      f"{SYNC_ATTR_CALLS[node.func.attr]}")
        elif (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "bool", "int")
            and node.args
            and isinstance(node.args[0], ast.Call)
            and (resolve(module, node.args[0].func) or "").startswith(
                ("jax.", "jnp.")
            )
        ):
            add(node, f"`{node.func.id}(<jax call>)` forces a device "
                      "sync on the result")
    return findings


def check_module(
    module: Module, zones: Sequence[str] = DEFAULT_ZONES
) -> List[Finding]:
    return check_traced_purity(module) + check_sync_zone(module, zones)
