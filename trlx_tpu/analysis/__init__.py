"""graft-lint: stdlib-``ast`` static enforcement of the repo's
hardest-won invariants (ISSUE 13). No jax import, no trlx_tpu import —
this package must stay loadable on a login node with nothing but the
standard library, and must NEVER be imported by the training path
(``bench.py --smoke`` and tests/test_graft_lint.py pin that).

Checkers (rule ids):

  donation      read-after-donation of buffers consumed by a
                ``donate_argnums``/``donate_argnames`` jit (the PR 3
                heap-corruption class: orbax-restored arrays fed to a
                donating train step, then read again).
  trace-purity  side effects inside functions traced by
                jit/pjit/scan/while_loop/fori_loop/cond/switch/
                shard_map/checkpoint: print, time.*, np.random/random,
                Python-state mutation, host-sync constructs.
  sync-zone     device-sync constructs (``.item()``,
                ``block_until_ready``, ``np.asarray``, ``device_get``,
                module-scope jax imports) in modules that claim
                "host-side, no device syncs" (``trlx_tpu/obs/``,
                ``utils/watchdog.py`` — plus any module whose docstring
                makes the claim).
  rng-manifest  chaos-site registry (utils/chaos.py FAULT_SITES) and
                guardrail-signal set (utils/guardrails.py) checked
                against committed manifests under tests/golden/ —
                append-only, automating the per-PR hand-check.
  config-docs   every dataclass field reachable from TRLConfig must be
                documented in docs/api.md and annotated in
                configs/test_config.yml, and vice versa.
  bad-pragma    a ``# graft-lint: allow[...]`` pragma with an unknown
                rule id or no reason (reasonless suppressions are not
                suppressions).

Findings are suppressible only via an inline pragma on the flagged
line::

    x = step(x, batch)  # graft-lint: allow[donation] rematerialized below

CLI: ``python scripts/graft_lint.py`` (see docs/static_analysis.md).
"""

from trlx_tpu.analysis.common import Finding  # noqa: F401
from trlx_tpu.analysis.runner import run_repo  # noqa: F401

RULES = (
    "donation",
    "trace-purity",
    "sync-zone",
    "rng-manifest",
    "config-docs",
    "bad-pragma",
    "lint-error",
)
