"""RNG-stream-discipline checker (rule ``rng-manifest``).

Two registries carry order/set contracts that every PR since PR 5 has
hand-checked in review:

* ``utils/chaos.py FAULT_SITES`` — the tuple ORDER keys each site's
  per-site RNG stream (``seed * 1_000_003 + index``): inserting,
  reordering or deleting a site silently shifts every later site's
  draws and breaks recorded chaos schedules. The committed manifest
  (``tests/golden/chaos_sites.json``) must be an exact PREFIX of the
  live tuple — new sites append, nothing else moves.
* ``utils/guardrails.py`` trip signals — the SET of signal strings
  (``*_SIGNAL`` constants plus ``self._trip("<literal>", ...)`` sites)
  is consumed by flight-recorder correlation, persisted trip tails and
  operator runbooks: a deleted/renamed signal orphans recorded
  histories. The committed manifest (``guardrail_signals.json``) must
  equal the live set; additions are appended via
  ``graft_lint.py --update-manifests``, deletions always fail (a real
  deletion is a hand edit the reviewer must see).

Extraction is AST-only so the check runs without importing trlx_tpu.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional, Tuple

from trlx_tpu.analysis.common import Finding

CHAOS_SOURCE = "trlx_tpu/utils/chaos.py"
GUARDRAILS_SOURCE = "trlx_tpu/utils/guardrails.py"
CHAOS_MANIFEST = "tests/golden/chaos_sites.json"
GUARDRAIL_MANIFEST = "tests/golden/guardrail_signals.json"


def extract_chaos_sites(source: str) -> Tuple[List[str], int]:
    """(ordered FAULT_SITES entries, assignment line) from chaos.py."""
    tree = ast.parse(source)
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if any(
            isinstance(t, ast.Name) and t.id == "FAULT_SITES"
            for t in node.targets
        ):
            val = ast.literal_eval(node.value)
            return [str(v) for v in val], node.lineno
    raise ValueError("FAULT_SITES tuple not found")


def extract_guardrail_signals(source: str) -> Tuple[List[str], Dict[str, int]]:
    """(sorted signal names, name -> first-seen line). Signals are the
    module-level ``*_SIGNAL`` string constants plus every literal first
    argument of a ``._trip("...")`` / ``.trip("...")`` call."""
    tree = ast.parse(source)
    lines: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (
                    isinstance(t, ast.Name)
                    and t.id.endswith("_SIGNAL")
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                ):
                    lines.setdefault(node.value.value, node.lineno)
        elif isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in ("_trip", "trip")
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                lines.setdefault(node.args[0].value, node.lineno)
    return sorted(lines), lines


def _load_manifest(path: str) -> Optional[dict]:
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        return json.load(f)


def check(repo: str) -> List[Finding]:
    findings: List[Finding] = []

    # --- chaos sites: committed list must be a prefix of the live one
    chaos_path = os.path.join(repo, CHAOS_SOURCE)
    manifest_path = os.path.join(repo, CHAOS_MANIFEST)
    try:
        with open(chaos_path) as f:
            live, line = extract_chaos_sites(f.read())
    except (OSError, ValueError) as e:
        return [Finding("rng-manifest", CHAOS_SOURCE, 1,
                        f"cannot extract FAULT_SITES: {e}")]
    committed = _load_manifest(manifest_path)
    if committed is None:
        findings.append(Finding(
            "rng-manifest", CHAOS_MANIFEST, 1,
            f"missing manifest — run `python scripts/graft_lint.py "
            "--update-manifests` to commit the current chaos-site order",
            snippet="chaos_sites.json",
        ))
    else:
        sites = committed.get("sites", [])
        if live[:len(sites)] != sites:
            # name the first divergence so the fix is obvious
            i = next(
                (k for k, (a, b) in enumerate(zip(sites, live)) if a != b),
                min(len(sites), len(live)),
            )
            was = sites[i] if i < len(sites) else "<end>"
            now = live[i] if i < len(live) else "<deleted>"
            findings.append(Finding(
                "rng-manifest", CHAOS_SOURCE, line,
                "FAULT_SITES diverged from the committed manifest at "
                f"index {i}: manifest has {was!r}, source has {now!r}. "
                "The registry is APPEND-ONLY — each site's RNG stream "
                "is keyed by its index, so inserts/reorders/deletes "
                "silently shift every later site's draws. Move new "
                "sites to the end; a genuine removal is a hand edit of "
                f"{CHAOS_MANIFEST} the reviewer must see",
                snippet=f"FAULT_SITES[{i}] {was!r} -> {now!r}",
            ))
        elif len(live) > len(sites):
            extra = live[len(sites):]
            findings.append(Finding(
                "rng-manifest", CHAOS_SOURCE, line,
                f"new chaos sites {extra} appended but not yet in the "
                f"manifest — run `python scripts/graft_lint.py "
                "--update-manifests` (append-only) and commit it",
                snippet=f"unmanifested: {','.join(extra)}",
            ))

    # --- guardrail signals: committed set must equal the live set
    guard_path = os.path.join(repo, GUARDRAILS_SOURCE)
    gman_path = os.path.join(repo, GUARDRAIL_MANIFEST)
    try:
        with open(guard_path) as f:
            signals, sig_lines = extract_guardrail_signals(f.read())
    except (OSError, SyntaxError) as e:
        return findings + [Finding("rng-manifest", GUARDRAILS_SOURCE, 1,
                                   f"cannot extract signals: {e}")]
    gman = _load_manifest(gman_path)
    if gman is None:
        findings.append(Finding(
            "rng-manifest", GUARDRAIL_MANIFEST, 1,
            "missing manifest — run `python scripts/graft_lint.py "
            "--update-manifests` to commit the current signal set",
            snippet="guardrail_signals.json",
        ))
        return findings
    known = gman.get("signals", [])
    removed = [s for s in known if s not in signals]
    added = [s for s in signals if s not in known]
    if removed:
        findings.append(Finding(
            "rng-manifest", GUARDRAILS_SOURCE, 1,
            f"guardrail signal(s) {removed} deleted/renamed — recorded "
            "trip histories, flight-recorder correlation and runbooks "
            "reference them by name. A genuine removal is a hand edit "
            f"of {GUARDRAIL_MANIFEST} the reviewer must see",
            snippet=f"removed: {','.join(removed)}",
        ))
    for s in added:
        findings.append(Finding(
            "rng-manifest", GUARDRAILS_SOURCE, sig_lines.get(s, 1),
            f"new guardrail signal {s!r} is not in the manifest — run "
            "`python scripts/graft_lint.py --update-manifests` and "
            "commit it (and document the signal in docs/robustness.md)",
            snippet=f"unmanifested: {s}",
        ))
    return findings


def update(repo: str) -> List[str]:
    """Regenerate both manifests, append-only. Returns human-readable
    notes; raises on a non-append chaos change (the one thing this
    tool must never paper over)."""
    notes = []
    with open(os.path.join(repo, CHAOS_SOURCE)) as f:
        live, _ = extract_chaos_sites(f.read())
    cpath = os.path.join(repo, CHAOS_MANIFEST)
    committed = _load_manifest(cpath)
    if committed is not None:
        sites = committed.get("sites", [])
        if live[:len(sites)] != sites:
            raise ValueError(
                "refusing to update chaos_sites.json: the live "
                "FAULT_SITES is not an append of the committed order "
                "(inserts/reorders/deletes shift per-site RNG streams)."
                " Fix the registry, or hand-edit the manifest if the "
                "break is truly intended"
            )
    os.makedirs(os.path.dirname(cpath), exist_ok=True)
    with open(cpath, "w") as f:
        json.dump({
            "source": CHAOS_SOURCE,
            "discipline": "append-only (index keys each site's RNG stream)",
            "sites": live,
        }, f, indent=2)
        f.write("\n")
    notes.append(f"{CHAOS_MANIFEST}: {len(live)} sites")

    with open(os.path.join(repo, GUARDRAILS_SOURCE)) as f:
        signals, _ = extract_guardrail_signals(f.read())
    gpath = os.path.join(repo, GUARDRAIL_MANIFEST)
    gman = _load_manifest(gpath)
    if gman is not None:
        removed = [s for s in gman.get("signals", []) if s not in signals]
        if removed:
            raise ValueError(
                f"refusing to update guardrail_signals.json: signal(s) "
                f"{removed} would be deleted. Recorded trip histories "
                "reference them; hand-edit the manifest if the removal "
                "is truly intended"
            )
    with open(gpath, "w") as f:
        json.dump({
            "source": GUARDRAILS_SOURCE,
            "discipline": "no deletes/renames; additions via --update-manifests",
            "signals": signals,
        }, f, indent=2)
        f.write("\n")
    notes.append(f"{GUARDRAIL_MANIFEST}: {len(signals)} signals")
    return notes
