"""Config<->docs drift checker (rule ``config-docs``).

Every dataclass field reachable from ``TRLConfig`` (the six sections
plus every registered method config) must be:

* mentioned in ``docs/api.md`` (word match — the doc owes the field at
  least a sentence), and
* annotated in ``configs/test_config.yml`` ("every config field,
  annotated" is that file's contract; commented annotation lines
  count, they are how default-off subsections document themselves),

and vice versa — no phantoms:

* every *actual* (uncommented) key in test_config.yml must be a known
  field of its section (keys nested under a dict-typed field are that
  subsystem's own schema and out of scope here), and
* every backticked dotted reference in api.md whose prefix names a
  section (``train.foo``, ``model.bar``, ``ppo.baz`` ...) must resolve
  to a real field.

AST-only on the config modules; no trlx_tpu import.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from trlx_tpu.analysis.common import Finding

CONFIG_MODULES = (
    "trlx_tpu/data/configs.py",
    "trlx_tpu/data/method_configs.py",
)
DOCS_PATH = "docs/api.md"
YML_PATH = "configs/test_config.yml"

# doc-reference prefixes -> section key ('method:<Class>' selects one
# method config; bare 'method' means any registered method's field)
_METHOD_ALIAS_RE = re.compile(r"^(\w+)Config$")


@dataclass
class FieldInfo:
    name: str
    cls: str
    section: str
    file: str
    line: int
    is_dict: bool  # Dict/dict/Any-typed: nested keys are free-form


def _annotation_is_dict(node) -> bool:
    src = ast.dump(node)
    return any(k in src for k in ("'Dict'", "'dict'", "'Any'"))


def _is_dataclass(node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = getattr(target, "id", getattr(target, "attr", ""))
        if name == "dataclass":
            return True
    return False


def collect_fields(
    repo: str, config_modules: Tuple[str, ...] = CONFIG_MODULES
) -> Tuple[List[FieldInfo], Dict[str, List[str]]]:
    """(all reachable fields, section -> class names). The section map
    comes from the ``_SECTIONS`` literal in configs.py; every dataclass
    in method_configs.py maps to the ``method`` section (the registry
    makes them all reachable via ``method.name``)."""
    fields: List[FieldInfo] = []
    sections: Dict[str, List[str]] = {}
    class_fields: Dict[str, List[Tuple[str, int, bool]]] = {}
    cls_file: Dict[str, str] = {}
    section_of_cls: Dict[str, str] = {}

    for rel in config_modules:
        path = os.path.join(repo, rel)
        with open(path) as f:
            tree = ast.parse(f.read())
        is_methods = "method_configs" in rel
        for node in tree.body:
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target] if isinstance(node, ast.AnnAssign)
                else []
            )
            if getattr(node, "value", None) is not None and any(
                isinstance(t, ast.Name) and t.id == "_SECTIONS"
                for t in targets
            ):
                # (("model", ModelConfig), ...) — names are Name nodes
                for el in getattr(node.value, "elts", []):
                    if isinstance(el, ast.Tuple) and len(el.elts) == 2:
                        key = getattr(el.elts[0], "value", None)
                        cls = getattr(el.elts[1], "id", None)
                        if key and cls:
                            sections.setdefault(key, []).append(cls)
                            section_of_cls[cls] = key
            if not (isinstance(node, ast.ClassDef) and _is_dataclass(node)):
                continue
            rows = []
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    rows.append((
                        stmt.target.id, stmt.lineno,
                        _annotation_is_dict(stmt.annotation),
                    ))
            class_fields[node.name] = rows
            cls_file[node.name] = rel
            if is_methods:
                sections.setdefault("method", []).append(node.name)
                section_of_cls[node.name] = "method"

    for cls, rows in class_fields.items():
        section = section_of_cls.get(cls)
        if section is None:
            continue  # TRLConfig itself: its fields ARE the sections
        for name, line, is_dict in rows:
            fields.append(FieldInfo(
                name=name, cls=cls, section=section,
                file=cls_file[cls], line=line, is_dict=is_dict,
            ))
    return fields, sections


def _doc_prefixes(sections: Dict[str, List[str]]) -> Dict[str, List[str]]:
    """'train' -> [TrainConfig], 'ppo' -> [PPOConfig], ..."""
    out = {k: list(v) for k, v in sections.items()}
    for cls in sections.get("method", []):
        m = _METHOD_ALIAS_RE.match(cls)
        if m and m.group(1).lower() != "method":
            out[m.group(1).lower()] = [cls]
    return out


_BACKTICK_RE = re.compile(r"`([A-Za-z_][\w.]*(?:\.\*)?)`")
_YML_KEY_RE = re.compile(r"(?<![\w.]){name}\s*:")


def check(
    repo: str,
    config_modules: Tuple[str, ...] = CONFIG_MODULES,
    docs_path: str = DOCS_PATH,
    yml_path: str = YML_PATH,
) -> List[Finding]:
    import yaml

    findings: List[Finding] = []
    try:
        fields, sections = collect_fields(repo, config_modules)
    except (OSError, SyntaxError) as e:
        return [Finding("config-docs", config_modules[0], 1,
                        f"cannot parse config modules: {e}")]
    try:
        with open(os.path.join(repo, docs_path)) as f:
            docs = f.read()
        with open(os.path.join(repo, yml_path)) as f:
            yml_text = f.read()
    except OSError as e:
        return [Finding("config-docs", docs_path, 1, f"unreadable: {e}")]

    # the dict-subkey exemption is structural: only depth-1 yml keys
    # are checked below, and everything deeper sits under a dict-typed
    # field by construction of the config schema
    by_section: Dict[str, set] = {}
    for fi in fields:
        by_section.setdefault(fi.section, set()).add(fi.name)

    # --- direction 1: every field documented + annotated -------------
    for fi in fields:
        # plain word boundary: a dotted mention (`train.batch_size`)
        # counts as documentation of the field
        word = re.compile(rf"(?<!\w){re.escape(fi.name)}(?!\w)")
        if not word.search(docs):
            findings.append(Finding(
                "config-docs", fi.file, fi.line,
                f"{fi.cls}.{fi.name} (section `{fi.section}`) is not "
                f"mentioned anywhere in {docs_path} — document it "
                "(or drop the field)",
                snippet=f"{fi.cls}.{fi.name} undocumented",
            ))
        if not re.search(
            _YML_KEY_RE.pattern.format(name=re.escape(fi.name)), yml_text
        ):
            findings.append(Finding(
                "config-docs", fi.file, fi.line,
                f"{fi.cls}.{fi.name} (section `{fi.section}`) is not "
                f"annotated in {yml_path} — that file's contract is "
                "'every config field, annotated' (a commented "
                "annotation line counts)",
                snippet=f"{fi.cls}.{fi.name} unannotated",
            ))

    # --- direction 2a: no phantom yml keys ---------------------------
    try:
        data = yaml.safe_load(yml_text) or {}
    except yaml.YAMLError as e:
        return findings + [
            Finding("config-docs", yml_path, 1, f"unparseable YAML: {e}")
        ]
    yml_lines = yml_text.splitlines()

    def line_of(key: str) -> int:
        pat = re.compile(rf"^\s*{re.escape(key)}\s*:")
        for i, text in enumerate(yml_lines, start=1):
            if pat.match(text):
                return i
        return 1

    for section, content in (data.items() if isinstance(data, dict) else ()):
        known = by_section.get(section)
        if known is None:
            findings.append(Finding(
                "config-docs", yml_path, line_of(section),
                f"unknown config section {section!r} (known: "
                f"{sorted(by_section)})",
                snippet=f"section {section}",
            ))
            continue
        if not isinstance(content, dict):
            continue
        for key in content:
            if key not in known:
                findings.append(Finding(
                    "config-docs", yml_path, line_of(key),
                    f"{section}.{key} is annotated in {yml_path} but "
                    "no reachable config dataclass has that field — "
                    "phantom annotation (stale rename?)",
                    snippet=f"phantom yml key {section}.{key}",
                ))

    # --- direction 2b: no phantom doc references ---------------------
    prefixes = _doc_prefixes(sections)
    for i, text in enumerate(docs.splitlines(), start=1):
        for m in _BACKTICK_RE.finditer(text):
            parts = m.group(1).split(".")
            if len(parts) < 2 or parts[-1] == "py":
                continue  # `ppo.py`-style file references, not config paths
            head, field = parts[0], parts[1]
            if head == "method" and field in prefixes and len(parts) > 2:
                # `method.grpo.*` — method-alias hop, resolve the rest
                head, field = field, parts[2]
            if head not in prefixes or field in ("*",):
                continue
            classes = prefixes[head]
            known = set()
            for cls in classes:
                known |= {
                    fi.name for fi in fields if fi.cls == cls
                }
            if field not in known:
                findings.append(Finding(
                    "config-docs", docs_path, i,
                    f"`{m.group(1)}` in {docs_path} references a field "
                    f"`{field}` that no {'/'.join(classes)} dataclass "
                    "has — phantom documentation (stale rename?)",
                    snippet=f"phantom doc ref {m.group(1)}",
                ))
    return findings
