"""Donation-safety checker (rule ``donation``).

The PR 3 heap-corruption class: a buffer handed to a jit compiled with
``donate_argnums``/``donate_argnames`` is dead the moment the call is
dispatched — XLA may reuse its memory for the outputs. Reading the old
binding afterwards (before it is reassigned) reads freed storage:
orbax-restored params fed to the donating train step and then consumed
again was exactly that bug.

This checker:

1. finds every donating jit site — ``jax.jit(f, donate_argnums=...)`` /
   ``pjit`` calls and ``@partial(jax.jit, donate_argnums=...)``
   decorators with a non-empty donation spec;
2. resolves donating *callables*: names/attributes bound to a donating
   jit (``self._train_step = jax.jit(...)``), functions decorated
   donating, and — one level of indirection — names bound to a call of
   a function that *returns* a donating jit (the repo's
   ``make_train_step()`` factory idiom; the factory registry is shared
   across modules so ``trainer.make_train_step()`` resolves from any
   file);
3. at each call site of a donating callable, takes the caller bindings
   passed in donated positions and flags any read of those bindings
   after the call, before reassignment, within the enclosing function.

The dataflow is a straight-line, source-order approximation: a read
textually *before* the call inside the same loop body is out of scope
(documented limitation, docs/static_analysis.md). Metadata-only
attribute reads (``.is_deleted``, ``.sharding``, ``.shape``,
``.dtype``, ``.ndim``, ``.aval``) are not buffer reads and are
whitelisted — the memory doctor legitimately probes ``is_deleted`` on
possibly-donated trees.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from trlx_tpu.analysis.common import Finding, Module, dotted, resolve

JIT_FNS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
PARTIAL_FNS = {"functools.partial", "partial"}

METADATA_ATTRS = {"is_deleted", "sharding", "shape", "dtype", "ndim", "aval"}

# store events sort after every load on their own statement's last line
_END_OF_LINE = 1 << 20


def _donated_indices(
    module: Module, call: ast.Call, fdef=None
) -> Optional[Tuple[int, ...]]:
    """Donated indices of a jax.jit/pjit call in the jitted FUNCTION's
    own parameter space, or None when the call donates nothing (or the
    spec is not statically constant — conservatively treated as
    non-donating, noted in the docs).

    ``fdef`` pins the jitted function when the caller already knows it
    (the decorator form, where ``call.args[0]`` is ``jax.jit`` itself,
    not the function). argnames resolve against the function's params;
    for the *call* form ``jax.jit(self._step, ...)`` they are shifted
    past ``self`` here because bound-method call sites never pass it —
    the decorator path applies that shift itself, uniformly with
    argnums."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            try:
                val = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                return None
            if isinstance(val, int):
                return (val,)
            if isinstance(val, (tuple, list)) and val:
                return tuple(int(v) for v in val)
            return None
        if kw.arg == "donate_argnames":
            try:
                names = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                return None
            if isinstance(names, str):
                names = (names,)
            bound_call_form = fdef is None
            if fdef is None:
                fdef = _local_function_def(
                    module, call.args[0] if call.args else None
                )
            if fdef is None or not names:
                return None
            params = [a.arg for a in fdef.args.args]
            shift = (
                1 if bound_call_form and params[:1] == ["self"] else 0
            )
            idx = tuple(
                params.index(n) - shift for n in names if n in params
            )
            return idx or None
    return None


def _local_function_def(module: Module, node) -> Optional[ast.FunctionDef]:
    name = None
    if isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Attribute):
        name = node.attr
    if name is None:
        return None
    for n in ast.walk(module.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n.name == name:
            return n
    return None


def _is_jit(module: Module, node) -> bool:
    if not isinstance(node, (ast.Name, ast.Attribute)):
        return False
    if resolve(module, node) in JIT_FNS:
        return True
    return (dotted(node) or "").split(".")[-1] in ("jit", "pjit")


def _donating_jit_call(
    module: Module, node, fdef=None
) -> Optional[Tuple[int, ...]]:
    """Donated indices when ``node`` is a donating jax.jit/pjit(...) or
    partial(jax.jit, ...) call expression; None otherwise. ``fdef``
    names the decorated function in the decorator form (where the
    jitted function is not among the call's args)."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if (
        isinstance(fn, (ast.Name, ast.Attribute))
        and resolve(module, fn) in PARTIAL_FNS
        and node.args
        and _is_jit(module, node.args[0])
    ):
        return _donated_indices(module, node, fdef)
    if _is_jit(module, fn):
        return _donated_indices(module, node, fdef)
    return None


def _donated_names(module: Module, call: ast.Call, fdef=None) -> Tuple[str, ...]:
    """Donated parameter NAMES of this jit call, when resolvable —
    call sites may pass donated buffers by keyword, and positional
    indices alone cannot see those."""
    argnames: Tuple[str, ...] = ()
    argnums: Tuple[int, ...] = ()
    for kw in call.keywords:
        if kw.arg in ("donate_argnames", "donate_argnums"):
            try:
                val = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                return ()
            if kw.arg == "donate_argnames":
                argnames = (val,) if isinstance(val, str) else tuple(val)
            else:
                argnums = (val,) if isinstance(val, int) else tuple(val)
    if argnames:
        return argnames
    if not argnums:
        return ()
    f = fdef or _local_function_def(
        module, call.args[0] if call.args else None
    )
    if f is None:
        return ()
    params = [a.arg for a in f.args.args]
    # the call form jits a BOUND method: argnums index past `self`
    shift = 1 if fdef is None and params[:1] == ["self"] else 0
    return tuple(
        params[i + shift] for i in argnums if i + shift < len(params)
    )


def collect_factories(module: Module) -> Dict[str, Tuple]:
    """Function name -> (donated indices, donated param names), for
    every function in this module that returns a donating jit (the
    make_train_step idiom)."""
    out: Dict[str, Tuple] = {}
    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Return) and sub.value is not None:
                idx = _donating_jit_call(module, sub.value)
                if idx:
                    out[node.name] = (
                        idx, _donated_names(module, sub.value)
                    )
    return out


@dataclass
class _Callable:
    key: str  # dotted binding ('step', 'self._fused_train_step') or def name
    indices: Tuple[int, ...]
    line: int
    names: Tuple[str, ...] = ()  # donated params, for keyword call sites


def _collect_donating_callables(
    module: Module, factories: Dict[str, Tuple]
) -> List[_Callable]:
    out: List[_Callable] = []
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # @partial(jax.jit, donate_argnums=...): argnums index the
            # function's own params, so bound-method call sites see
            # them shifted past `self`
            for dec in node.decorator_list:
                idx = _donating_jit_call(module, dec, fdef=node)
                if idx:
                    params = [a.arg for a in node.args.args]
                    shift = 1 if params[:1] == ["self"] else 0
                    call_idx = tuple(i - shift for i in idx if i - shift >= 0)
                    if call_idx:
                        out.append(_Callable(
                            node.name, call_idx, node.lineno,
                            _donated_names(module, dec, fdef=node),
                        ))
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            idx = _donating_jit_call(module, node.value)
            names: Tuple[str, ...] = ()
            if idx is not None:
                names = _donated_names(module, node.value)
            else:
                fn = node.value.func
                fname = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None
                )
                if fname in factories:
                    idx, names = factories[fname]
            if idx:
                for tgt in node.targets:
                    key = dotted(tgt)
                    if key:
                        out.append(_Callable(key, idx, node.lineno, names))
    return out


class _ScopeIndex(ast.NodeVisitor):
    """Map every node to its innermost enclosing function."""

    def __init__(self):
        self.scope_of: Dict[ast.AST, ast.AST] = {}
        self._stack: List[ast.AST] = []

    def generic_visit(self, node):
        self.scope_of[node] = self._stack[-1] if self._stack else None
        is_fn = isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        )
        if is_fn:
            self._stack.append(node)
        super().generic_visit(node)
        if is_fn:
            self._stack.pop()


def _flat_targets(target) -> List[ast.AST]:
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for el in target.elts:
            out.extend(_flat_targets(el))
        return out
    return [target]


def _binding_events(scope: ast.AST, key: str):
    """Sorted ((line, col), 'load'|'store', node) events for ``key``
    inside ``scope``. Store positions use the end of the enclosing
    statement: the value (possibly the donating call) is fully
    evaluated before the binding lands."""
    events = []

    def load(node):
        events.append(((node.lineno, node.col_offset), "load", node))

    def store(stmt):
        events.append(((stmt.end_lineno, _END_OF_LINE), "store", stmt))

    def visit_expr(node):
        # maximal dotted chains are handled whole, so `x.sharding`
        # consults the metadata whitelist exactly once
        if isinstance(node, (ast.Name, ast.Attribute)):
            d = dotted(node)
            if d is not None:
                if d == key:
                    if isinstance(getattr(node, "ctx", None), ast.Load):
                        load(node)
                elif d.startswith(key + "."):
                    hop = d[len(key) + 1:].split(".")[0]
                    if hop not in METADATA_ATTRS:
                        load(node)
                return
        for ch in ast.iter_child_nodes(node):
            if isinstance(ch, ast.expr):
                visit_expr(ch)

    def visit_stmt(node):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for tgt in targets:
                for el in _flat_targets(tgt):
                    d = dotted(el)
                    if d == key:
                        store(node)
                    else:
                        # x[i] = v / x.attr = v reads x's buffer;
                        # also catches loads in subscript indices
                        visit_expr(el)
            if isinstance(node, ast.AugAssign) and dotted(node.target) == key:
                load(node)  # x += ... reads the old buffer first
            if node.value is not None:
                visit_expr(node.value)
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                if dotted(tgt) == key:
                    store(node)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            for el in _flat_targets(node.target):
                if dotted(el) == key:
                    events.append(
                        ((node.lineno, node.col_offset), "store", node)
                    )
            visit_expr(node.iter)
            for ch in node.body + node.orelse:
                visit_stmt(ch)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                visit_expr(item.context_expr)
                if item.optional_vars is not None and (
                    dotted(item.optional_vars) == key
                ):
                    events.append(
                        ((node.lineno, node.col_offset), "store", node)
                    )
            for ch in node.body:
                visit_stmt(ch)
            return
        for ch in ast.iter_child_nodes(node):
            if isinstance(ch, ast.expr):
                visit_expr(ch)
            elif isinstance(ch, ast.stmt):
                visit_stmt(ch)
            else:  # handlers / match cases: recurse one level
                for sub in ast.iter_child_nodes(ch):
                    if isinstance(sub, ast.stmt):
                        visit_stmt(sub)
                    elif isinstance(sub, ast.expr):
                        visit_expr(sub)

    for stmt in scope.body if hasattr(scope, "body") else [scope]:
        visit_stmt(stmt)
    return sorted(events, key=lambda e: e[0])


def check_module(
    module: Module, factories: Optional[Dict[str, Tuple]] = None
) -> List[Finding]:
    findings: List[Finding] = []
    local_factories = collect_factories(module)
    merged = dict(factories or {})
    merged.update(local_factories)
    callables = {
        c.key: c for c in _collect_donating_callables(module, merged)
    }

    scopes = _ScopeIndex()
    scopes.visit(module.tree)

    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        fkey = dotted(node.func)
        cand = callables.get(fkey) if fkey else None
        indices = cand.indices if cand else None
        names = cand.names if cand else ()
        if indices is None and isinstance(node.func, ast.Call):
            # immediate invocation: jax.jit(f, donate...)(args)
            indices = _donating_jit_call(module, node.func)
            if indices:
                names = _donated_names(module, node.func)
        if not indices:
            continue

        donated_args = [
            node.args[i] for i in indices if i < len(node.args)
        ] + [
            kw.value for kw in node.keywords if kw.arg in names
        ]
        scope = scopes.scope_of.get(node) or module.tree
        call_pos = (node.end_lineno, node.end_col_offset)
        for i, arg in enumerate(donated_args):
            arg_key = dotted(arg)
            if arg_key is None:
                continue  # expression args (copies, literals) own no binding
            events = _binding_events(scope, arg_key)
            post = [e for e in events if e[0] > call_pos]
            if not post or post[0][1] != "load":
                continue
            pos = post[0][0]
            findings.append(Finding(
                "donation", module.path, pos[0],
                f"`{arg_key}` is donated to `{fkey or 'a jitted fn'}` "
                f"at line {node.lineno} (donate arg {i}) and read again "
                "here before reassignment — the buffer may already be "
                "reused by XLA (the PR 3 bug class); reassign it from "
                "the call's outputs or pass a copy",
                snippet=module.line_at(pos[0]),
            ))
    return findings
