"""graft-lint orchestration: run every checker over a tree, apply
pragmas, and serialize/compare baselines. Stdlib only."""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

from trlx_tpu.analysis import config_docs, donation, manifests, purity
from trlx_tpu.analysis.common import (
    Finding,
    apply_pragmas,
    collect_pragmas,
    iter_python_files,
    parse_module,
    pragma_findings,
    read_source,
)

BASELINE_VERSION = 1


def lint_paths(
    repo: str,
    rel_paths: Sequence[str],
    zones: Sequence[str] = purity.DEFAULT_ZONES,
) -> List[Finding]:
    """Donation + purity + sync-zone + pragma checks over specific
    python files (repo-relative). Manifest and config-docs checks are
    repo-level and live in :func:`run_repo`."""
    modules = []
    findings: List[Finding] = []
    for rel in rel_paths:
        abs_path = os.path.join(repo, rel)
        try:
            source = read_source(abs_path)
        except OSError as e:
            findings.append(
                Finding("lint-error", rel, 1, f"unreadable: {e}",
                        snippet=f"unreadable {rel}")
            )
            continue
        mod = parse_module(rel, source)
        if mod is None:
            # tier-1 flake8 owns syntax errors; unparseable files are
            # simply out of lint scope
            continue
        modules.append(mod)

    # donation factories (make_train_step & co) resolve cross-module
    factories: Dict[str, tuple] = {}
    for mod in modules:
        factories.update(donation.collect_factories(mod))

    for mod in modules:
        per_file: List[Finding] = []
        per_file += donation.check_module(mod, factories)
        per_file += purity.check_module(mod, zones)
        per_file += pragma_findings(mod.path, mod.source)
        apply_pragmas(per_file, collect_pragmas(mod.source))
        findings += per_file
    return findings


def run_repo(
    repo: str,
    paths: Optional[Sequence[str]] = None,
    zones: Sequence[str] = purity.DEFAULT_ZONES,
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Full lint. ``paths`` restricts the per-file checkers (the
    repo-level manifest/config-docs checks still run unless filtered
    out via ``rules``)."""
    explicit_paths = paths is not None
    if paths is None:
        paths = [rel for rel, _ in iter_python_files(repo)]
    findings = lint_paths(repo, paths, zones)
    # repo-level checks are skipped when the caller pinned explicit
    # files (the CLI's fixture mode: lint THIS snippet)
    if not explicit_paths:
        repo_level: List[Finding] = []
        repo_level += manifests.check(repo)
        try:
            repo_level += config_docs.check(repo)
        except ImportError:
            # pyyaml missing: the config<->yml check needs it; the
            # environment always has it in CI (tier-1 imports yaml)
            repo_level.append(Finding(
                "config-docs", config_docs.YML_PATH, 1,
                "pyyaml unavailable — config<->docs check skipped",
            ))
        for f in repo_level:
            abs_path = os.path.join(repo, f.file)
            if os.path.isfile(abs_path):
                try:
                    apply_pragmas([f], collect_pragmas(read_source(abs_path)))
                except OSError:
                    pass
        findings += repo_level
    if rules:
        # lint-error (an unreadable/typo'd path) must never be
        # filterable into a silent clean exit
        findings = [
            f for f in findings if f.rule in rules or f.rule == "lint-error"
        ]
    return findings


def active(findings: Sequence[Finding]) -> List[Finding]:
    return [f for f in findings if f.suppressed_by is None]


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    """Machine-readable findings snapshot for ``--diff`` (future PRs
    get incremental signal: only NEW findings fail)."""
    payload = {
        "version": BASELINE_VERSION,
        "findings": [f.to_dict() for f in active(findings)],
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")


def diff_against(path: str, findings: Sequence[Finding]) -> List[Finding]:
    """Findings not present in the baseline (matched by stable key:
    rule + file + flagged source text, line-number independent)."""
    with open(path) as f:
        payload = json.load(f)
    if payload.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {payload.get('version')!r}, "
            f"expected {BASELINE_VERSION} — regenerate with --baseline"
        )
    known = {row["key"] for row in payload.get("findings", [])}
    return [f for f in active(findings) if f.key not in known]
