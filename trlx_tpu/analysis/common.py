"""Shared graft-lint plumbing: findings, pragmas, module parsing, and
import-alias resolution. Stdlib only — see the package docstring."""

from __future__ import annotations

import ast
import hashlib
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

KNOWN_RULES = (
    "donation",
    "trace-purity",
    "sync-zone",
    "rng-manifest",
    "config-docs",
    "bad-pragma",
    "lint-error",  # I/O failures of the lint itself; never filterable
)

# inline suppression grammar: `# graft-lint: allow[<rule>] <reason>`;
# several allow[...] groups may share one comment
_PRAGMA_RE = re.compile(
    r"graft-lint:\s*allow\[(?P<rule>[a-z0-9_-]+)\]\s*"
    r"(?P<reason>(?:(?!graft-lint:)[^#])*)"
)


@dataclass
class Finding:
    """One lint finding, anchored to a repo-relative file:line."""

    rule: str
    file: str
    line: int
    message: str
    snippet: str = ""
    suppressed_by: Optional[str] = None  # pragma reason when suppressed

    @property
    def key(self) -> str:
        """Stable identity for baseline/diff: deliberately excludes the
        line number (pure line drift must not resurface a triaged
        finding) but includes the flagged source text."""
        basis = f"{self.rule}|{self.file}|{self.snippet.strip()}"
        return hashlib.sha1(basis.encode()).hexdigest()[:16]

    def to_dict(self) -> Dict:
        return {
            "rule": self.rule,
            "file": self.file,
            "line": self.line,
            "message": self.message,
            "snippet": self.snippet.strip(),
            "key": self.key,
        }

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Pragma:
    rule: str
    reason: str
    line: int


def collect_pragmas(source: str) -> Dict[int, List[Pragma]]:
    """Line -> pragmas on that line. A pragma only ever suppresses
    findings anchored to its own line (inline discipline: the
    suppression sits where the reviewer reads the flagged code)."""
    out: Dict[int, List[Pragma]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        if "graft-lint" not in text:
            continue
        comment = text.split("#", 1)[1] if "#" in text else text
        for m in _PRAGMA_RE.finditer(comment):
            out.setdefault(i, []).append(
                Pragma(m.group("rule"), m.group("reason").strip(), i)
            )
    return out


def pragma_findings(path: str, source: str) -> List[Finding]:
    """Malformed pragmas are findings themselves: an unknown rule id or
    a missing reason must fail loudly, or typos become silent
    unsuppressed noise and reasonless suppressions rot."""
    out = []
    for line, pragmas in collect_pragmas(source).items():
        for p in pragmas:
            if p.rule not in KNOWN_RULES:
                out.append(Finding(
                    "bad-pragma", path, line,
                    f"pragma allows unknown rule {p.rule!r} "
                    f"(known: {', '.join(KNOWN_RULES)})",
                    snippet=f"allow[{p.rule}]",
                ))
            elif not p.reason:
                out.append(Finding(
                    "bad-pragma", path, line,
                    f"pragma allow[{p.rule}] carries no reason — a "
                    "suppression must say why the finding is intended",
                    snippet=f"allow[{p.rule}] @L{line}",
                ))
    return out


def apply_pragmas(
    findings: List[Finding], pragmas: Dict[int, List[Pragma]]
) -> List[Finding]:
    """Mark findings suppressed by a well-formed same-line pragma."""
    for f in findings:
        for p in pragmas.get(f.line, []):
            if p.rule == f.rule and p.reason and p.rule in KNOWN_RULES:
                f.suppressed_by = p.reason
    return findings


@dataclass
class Module:
    """A parsed python file plus its import-alias map."""

    path: str  # repo-relative, forward slashes
    source: str
    tree: ast.AST
    aliases: Dict[str, str] = field(default_factory=dict)

    @property
    def lines(self) -> List[str]:
        return self.source.splitlines()

    def line_at(self, lineno: int) -> str:
        ls = self.lines
        return ls[lineno - 1] if 1 <= lineno <= len(ls) else ""


def parse_module(path: str, source: str) -> Optional[Module]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return Module(path=path, source=source, tree=tree, aliases=aliases)


def dotted(node: ast.AST) -> Optional[str]:
    """Name/Attribute chain -> raw dotted string ('self.params',
    'jnp.asarray'); None for anything else (calls, subscripts...)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve(module: Module, node: ast.AST) -> Optional[str]:
    """Dotted chain with its root import-alias expanded to the
    canonical module path: `jnp.asarray` -> `jax.numpy.asarray`,
    `scan` (from jax.lax import scan) -> `jax.lax.scan`."""
    raw = dotted(node)
    if raw is None:
        return None
    root, _, rest = raw.partition(".")
    canon_root = module.aliases.get(root, root)
    return f"{canon_root}.{rest}" if rest else canon_root


def iter_python_files(
    root: str, subdirs: Iterable[str] = ("trlx_tpu", "scripts", "examples")
) -> List[Tuple[str, str]]:
    """(repo-relative path, absolute path) for every lintable .py file.

    Deliberately out of scope: tests (they hold known-bad fixture
    snippets) and this analysis package itself (its checker sources
    quote the very patterns they detect — rule tables, message strings
    — and would self-flag)."""
    out = []
    top = [f for f in os.listdir(root) if f.endswith(".py")]
    for f in sorted(top):
        out.append((f, os.path.join(root, f)))
    for sub in subdirs:
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            if os.path.basename(dirpath) == "trlx_tpu" and sub == "trlx_tpu":
                dirnames[:] = [d for d in dirnames if d != "analysis"]
            for f in sorted(filenames):
                if not f.endswith(".py"):
                    continue
                ap = os.path.join(dirpath, f)
                rp = os.path.relpath(ap, root).replace(os.sep, "/")
                out.append((rp, ap))
    return out


def read_source(abs_path: str) -> str:
    with open(abs_path, encoding="utf-8") as f:
        return f.read()
