"""DPO GPT2 on IMDB sentiment preference pairs: offline direct
preference optimization over (prompt, chosen, rejected) triples built
from labeled reviews — the chosen continuation comes from a positive
review, the rejected from a negative one. Requires HF hub access
(gpt2 weights + the IMDB dataset).

SMOKE=1 runs the SAME wiring air-gapped: a tiny random-init
transformer, the byte tokenizer and a synthetic separable preference
set, so CI executes this example's full train loop end to end.
"""

import os
from typing import List, Tuple

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_dpo_config

SMOKE = os.environ.get("SMOKE", "0") == "1"


def smoke_config() -> TRLConfig:
    """CI-sized smoke configuration: tiny random model, byte tokenizer,
    2 steps — everything else identical to the real run's wiring."""
    return default_dpo_config().evolve(
        model=dict(
            model_path="random", num_layers_unfrozen=-1,
            model_extra_configs={
                "transformer": dict(
                    hidden_size=16, n_layer=2, n_head=2, n_positions=64
                )
            },
        ),
        tokenizer=dict(tokenizer_path="byte"),
        train=dict(
            batch_size=8, total_steps=2, seq_length=16, eval_interval=2,
            checkpoint_interval=2, tracker=None,
        ),
        method=dict(gen_kwargs=dict(max_new_tokens=4, do_sample=False)),
    )


def imdb_preference_pairs(n_pairs: int = 2048) -> List[Tuple[str, str, str]]:
    """Zip positive/negative IMDB reviews into preference triples: the
    first words of the positive review are the prompt, its continuation
    the chosen completion, the negative review's text the rejected one."""
    from datasets import load_dataset

    imdb = load_dataset("imdb", split="train")
    pos = [t for t, l in zip(imdb["text"], imdb["label"]) if l == 1]
    neg = [t for t, l in zip(imdb["text"], imdb["label"]) if l == 0]
    pairs = []
    for p, n in list(zip(pos, neg))[:n_pairs]:
        words = p.split()
        prompt = " ".join(words[:4])
        chosen = " ".join(words[4:68])
        rejected = " ".join(n.split()[:64])
        if chosen and rejected:
            pairs.append((prompt, chosen, rejected))
    return pairs


def main(hparams={}):
    if SMOKE:
        config = TRLConfig.update(smoke_config().to_dict(), hparams)
        pairs = [
            (p, "aaaa", "zzzz") for p in
            ("the movie was", "I watched", "a review:", "honestly",
             "the acting", "what a film", "two hours", "the director")
        ] * 2
        return trlx_tpu.train(samples=pairs, config=config)

    config = TRLConfig.update(default_dpo_config().to_dict(), hparams)
    return trlx_tpu.train(samples=imdb_preference_pairs(), config=config)


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
