"""GRPO GPT2 on IMDB sentiment continuation: the PPO sentiments
workload (examples/ppo_sentiments.py) with the critic-free
group-relative trainer — 8 samples per prompt, advantages are the
per-group reward z-scores, no value head. Requires HF hub access
(gpt2-imdb weights + a sentiment classifier).

SMOKE=1 runs the SAME wiring air-gapped: a tiny random-init transformer
via model_extra_configs, the byte tokenizer, fixed prompts, and a
synthetic lexical-positivity reward standing in for the classifier —
so CI executes this example's full train loop end to end.
"""

import os
from typing import Dict, List

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_grpo_config

SMOKE = os.environ.get("SMOKE", "0") == "1"


def get_positive_score(scores: List[Dict[str, float]]) -> float:
    return dict(map(lambda x: tuple(x.values()), scores))["POSITIVE"]


def smoke_config() -> TRLConfig:
    """CI-sized smoke configuration: tiny random model, byte tokenizer,
    2 steps, groups of 4 — everything else identical to the real run's
    wiring."""
    return default_grpo_config().evolve(
        model=dict(
            model_path="random", num_layers_unfrozen=-1,
            model_extra_configs={
                "transformer": dict(
                    hidden_size=16, n_layer=2, n_head=2, n_positions=64
                )
            },
        ),
        tokenizer=dict(tokenizer_path="byte"),
        train=dict(
            batch_size=8, total_steps=2, seq_length=16, eval_interval=2,
            checkpoint_interval=2, tracker=None,
        ),
        method=dict(
            num_rollouts=8, chunk_size=8, group_size=4, grpo_epochs=1,
            gen_kwargs=dict(max_new_tokens=4, top_k=0, top_p=1.0, do_sample=True),
        ),
    )


def main(hparams={}):
    if SMOKE:
        config = TRLConfig.update(smoke_config().to_dict(), hparams)

        def reward_fn(samples: List[str], **kwargs) -> List[float]:
            # lexical positivity stand-in for the sentiment classifier
            return [float(s.count("a")) - 0.05 * len(s) for s in samples]

        prompts = ["the movie was", "I watched this and", "a review:",
                   "honestly the plot", "the acting", "what a film,",
                   "two hours of", "the director"] * 2
        return trlx_tpu.train(
            reward_fn=reward_fn,
            prompts=prompts,
            eval_prompts=prompts[:8],
            config=config,
        )

    config = TRLConfig.update(default_grpo_config().to_dict(), hparams)

    from datasets import load_dataset
    from transformers import pipeline as hf_pipeline

    sentiment_fn = hf_pipeline(
        "sentiment-analysis",
        "lvwerra/distilbert-imdb",
        top_k=2,
        truncation=True,
        batch_size=256,
    )

    def reward_fn(samples: List[str], **kwargs) -> List[float]:
        return list(map(get_positive_score, sentiment_fn(samples)))

    imdb = load_dataset("imdb", split="train+test")
    prompts = [" ".join(review.split()[:4]) for review in imdb["text"]]

    return trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=["I don't know much about Hungarian underground"] * 256,
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
