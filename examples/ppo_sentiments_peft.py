"""PPO GPT2 on IMDB with a LoRA adapter (parity:
/root/reference/examples/ppo_sentiments_peft.py). Only the adapters and
the value head train; the frozen base doubles as the KL reference, so
the hydra branch (and its memory) disappears entirely. Swap peft_config
for {"peft_type": "PROMPT_TUNING"/"PREFIX_TUNING", "num_virtual_tokens": 10}
to use virtual-token adapters instead.
"""

from typing import Dict, List

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_ppo_config


def get_positive_score(scores: List[Dict[str, float]]) -> float:
    return dict(map(lambda x: tuple(x.values()), scores))["POSITIVE"]


def main(hparams={}):
    config = TRLConfig.update(default_ppo_config().to_dict(), hparams)

    # any HF-peft-style dict works here (reference passes a peft.LoraConfig)
    config.model.peft_config = {
        "peft_type": "LORA",
        "r": 8,
        "lora_alpha": 32,
    }

    from datasets import load_dataset
    from transformers import pipeline as hf_pipeline

    sentiment_fn = hf_pipeline(
        "sentiment-analysis",
        "lvwerra/distilbert-imdb",
        top_k=2,
        truncation=True,
        batch_size=256,
    )

    def reward_fn(samples: List[str], **kwargs) -> List[float]:
        return list(map(get_positive_score, sentiment_fn(samples)))

    imdb = load_dataset("imdb", split="train+test")
    prompts = [" ".join(review.split()[:4]) for review in imdb["text"]]

    return trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=["I don't know much about Hungarian underground"] * 64,
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
