"""SFT GPT2 on positive IMDB reviews (parity:
/root/reference/examples/sft_sentiments.py)."""

from typing import Dict, List

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_sft_config


def get_positive_score(scores: List[Dict[str, float]]) -> float:
    return dict(map(lambda x: tuple(x.values()), scores))["POSITIVE"]


def main(hparams={}):
    config = TRLConfig.update(default_sft_config().to_dict(), hparams)

    from datasets import load_dataset
    from transformers import pipeline as hf_pipeline

    imdb = load_dataset("imdb", split="train")
    # fine-tune on positive reviews only
    samples = [sample["text"] for sample in imdb if sample["label"] == 1][:10000]

    sentiment_fn = hf_pipeline(
        "sentiment-analysis",
        "lvwerra/distilbert-imdb",
        top_k=2,
        truncation=True,
        batch_size=256,
    )

    def metric_fn(samples: List[str], **kwargs) -> Dict[str, List[float]]:
        return {"sentiments": list(map(get_positive_score, sentiment_fn(samples)))}

    return trlx_tpu.train(
        samples=samples,
        eval_prompts=["I don't know much about Hungarian underground"] * 64,
        metric_fn=metric_fn,
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
