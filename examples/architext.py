"""Toy PPO example: optimize textual interior designs toward the fewest
rooms (parity: /root/reference/examples/architext.py)."""

import trlx_tpu
from trlx_tpu.data.default_configs import default_ppo_config


def reward_fn(samples, **kwargs):
    "Gives a negative count of rooms for each sample"
    return [-sample.count(":") for sample in samples]


prompts = [
    "[prompt] the bedroom is adjacent to the living room [layout]",
    "[prompt] a bedroom is adjacent to the living room [layout]",
    "[prompt] the bedroom is adjacent to the kitchen [layout]",
    "[prompt] a bedroom is adjacent to the kitchen [layout]",
    "[prompt] the kitchen is adjacent to the bathroom [layout]",
    "[prompt] a bathroom is adjacent to the living room [layout]",
    "[prompt] the bathroom is adjacent to the living room [layout]",
    "[prompt] the bedroom is not adjacent to the living room [layout]",
    "[prompt] a bedroom is not adjacent to the living room [layout]",
    "[prompt] the bedroom is not adjacent to the kitchen [layout]",
    "[prompt] the kitchen is not adjacent to the bathroom [layout]",
]


def main(hparams={}):
    from trlx_tpu.data.configs import TRLConfig

    config = TRLConfig.update(default_ppo_config().to_dict(), hparams)
    return trlx_tpu.train(
        model_path="architext/gptj-162M", reward_fn=reward_fn,
        prompts=prompts, config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    main({} if len(sys.argv) == 1 else json.loads(sys.argv[1]))
