"""ILQL on flan-T5 over TL;DR comparison pairs (parity:
/root/reference/examples/summarize_rlhf/ilql_summarize_t5.py).

Offline RL on the human preference data directly: each comparison
contributes its chosen summary with reward +1 and its rejected summary
with reward -1 (the reference's `preprocess`), so no reward model is in
the training loop — the trained stage-2 RM only scores eval samples
through `metric_fn`, matching the reference's use of `rw_model` there.
"""

import os

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_ilql_config

default_config = default_ilql_config().evolve(
    train=dict(
        seq_length=550,
        batch_size=8,
        epochs=100,
        total_steps=5000,
        checkpoint_interval=10000,
        eval_interval=1000,
        checkpoint_dir="ckpts/ilql_summarize_t5",
        mesh={"dp": -1, "fsdp": 8, "tp": 1, "sp": 1},
        compute_dtype="bfloat16",
    ),
    model=dict(
        model_path="pvduy/flant5-xl_openai_tldr_sft",
        num_layers_unfrozen=-1,
        model_arch_type="seq2seq",
    ),
    tokenizer=dict(
        tokenizer_path="pvduy/flant5-xl_openai_tldr_sft", truncation_side="left"
    ),
    optimizer=dict(
        name="adamw",
        kwargs=dict(lr=1e-6, betas=(0.9, 0.95), eps=1.0e-8, weight_decay=1.0e-6),
    ),
    scheduler=dict(name="cosine_annealing", kwargs=dict(T_max=5000, eta_min=1e-6)),
    method=dict(
        tau=0.6,
        gamma=0.99,
        cql_scale=0.1,
        awac_scale=1,
        alpha=0.0001,
        beta=0,
        steps_for_target_q_sync=1,
        two_qs=True,
        gen_kwargs=dict(max_new_tokens=50, top_k=50, beta=1, temperature=1.0),
    ),
)


def main(hparams={}):
    config = TRLConfig.update(default_config.to_dict(), hparams)

    from datasets import load_dataset

    from examples.summarize_rlhf.ppo_summarize import make_rm_reward_fn

    rm_score = make_rm_reward_fn(
        os.environ.get("RM_DIR", "ckpts/reward_model"),
        max_length=config.train.seq_length,
    )

    def metric_fn(samples, **kwargs):
        return {"rewards": rm_score(samples).tolist()}

    # chosen summaries carry reward +1, rejected -1 — offline preference
    # data IS the dataset (ref ilql_summarize_t5.py preprocess)
    dataset = load_dataset("CarperAI/openai_summarize_comparisons")
    samples, rewards = [], []
    for x in dataset["train"]:
        prompt = x["prompt"] + " TL;DR:"
        samples.append([prompt, x["chosen"][7:]])
        rewards.append(1.0)
        samples.append([prompt, x["rejected"][7:]])
        rewards.append(-1.0)

    val = load_dataset("CarperAI/openai_summarize_tldr", split="valid")
    eval_prompts = list(val["prompt"])[:1000]

    return trlx_tpu.train(
        samples=samples,
        rewards=rewards,
        eval_prompts=eval_prompts,
        metric_fn=metric_fn,
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    main({} if len(sys.argv) == 1 else json.loads(sys.argv[1]))
