"""Stage 3: PPO against the trained reward model on TL;DR (parity:
/root/reference/examples/summarize_rlhf/trlx_gptj_text_summarization.py).
Reward = RM(sample) - RM(original human summary for that prompt)."""

import os

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_ppo_config

default_config = default_ppo_config().evolve(
    train=dict(
        seq_length=550,
        batch_size=16,
        total_steps=100000,
        eval_interval=200,
        checkpoint_interval=1000,
        checkpoint_dir="ckpts/ppo_summarize",
        mesh={"dp": -1, "fsdp": 8, "tp": 1, "sp": 1},
        compute_dtype="bfloat16",
    ),
    model=dict(
        model_path="ckpts/sft_summarize/best_checkpoint/hf_model",
        num_layers_unfrozen=8,
    ),
    tokenizer=dict(tokenizer_path="EleutherAI/gpt-j-6B", truncation_side="right"),
    optimizer=dict(kwargs=dict(lr=5e-6, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01)),
    method=dict(
        num_rollouts=128,
        chunk_size=16,
        ppo_epochs=4,
        init_kl_coef=0.1,
        target=6,
        horizon=10000,
        cliprange_reward=10,
        gen_kwargs=dict(max_new_tokens=50, do_sample=True, top_k=0, top_p=1.0),
    ),
)


def make_rm_reward_fn(rm_dir: str, max_length: int = 550):
    """Load the stage-2 reward model and score text on device."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import orbax.checkpoint as ocp

    from examples.summarize_rlhf.reward_model.train_reward_model import rm_forward
    from trlx_tpu.data.configs import TokenizerConfig
    from trlx_tpu.models.hf import load_pretrained
    from trlx_tpu.utils.tokenizers import load_tokenizer

    sft_dir = default_config.model.model_path
    lm, _, _ = load_pretrained(sft_dir)
    params = ocp.PyTreeCheckpointer().restore(
        os.path.join(os.path.abspath(rm_dir), "params")
    )
    tokenizer = load_tokenizer(TokenizerConfig(tokenizer_path=sft_dir))
    score = jax.jit(lambda ids, mask: rm_forward(lm, params, ids, mask))

    def rm_score(texts):
        enc = tokenizer(list(texts), truncation=True, padding="max_length",
                        max_length=max_length)
        out = score(
            jnp.asarray(enc["input_ids"], jnp.int32),
            jnp.asarray(enc["attention_mask"], jnp.int32),
        )
        return np.asarray(out)

    return rm_score


def main(hparams={}):
    config = TRLConfig.update(default_config.to_dict(), hparams)

    from datasets import load_dataset

    dataset = load_dataset("CarperAI/openai_summarize_tldr")
    prompt_label = {
        x["prompt"].strip(): x["label"] for split in ("train", "valid")
        for x in dataset[split]
    }
    rm_score = make_rm_reward_fn(os.environ.get("RM_DIR", "ckpts/reward_model"))

    def reward_fn(samples, prompts, outputs, **kwargs):
        # normalize against the human-written summary for the same prompt
        originals = [
            p.strip() + " " + prompt_label.get(p.strip(), "") for p in prompts
        ]
        return (rm_score(samples) - rm_score(originals)).tolist()

    prompts = [x["prompt"] for x in dataset["train"]]
    eval_prompts = [x["prompt"] for x in dataset["valid"]][:256]

    return trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts, eval_prompts=eval_prompts, config=config
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
