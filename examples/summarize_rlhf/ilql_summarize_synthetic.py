"""Air-gapped summarize-SHAPE ILQL on a first-party T5: offline RL on a
synthetic compressible-document task, scored by a ROUGE-1 proxy.

The real TL;DR pipeline (ilql_summarize_t5.py, parity with the
reference's examples/summarize_rlhf) needs the HF hub for flan-T5 and
the comparisons dataset — unreachable in a zero-egress environment. This
example keeps the SHAPE of that run so the learning curve is recordable
in-repo (docs/curves/): a seq2seq (T5) model, offline ILQL over
chosen/rejected summary pairs (+1 / -1 rewards, the reference's
`preprocess`), beta-swept eval generation, and a summary-quality metric.

Task: a "document" lists key-value records (`ka7 qb2 xc4 ...`); its
gold "summary" is the keys in order (`acx`). Corrupted summaries
(random letters) form the rejected side. The metric is unigram-F1
between the generated summary and the gold keys — the ROUGE-1 proxy.
"""

from __future__ import annotations

from typing import List

import numpy as np

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_ilql_config

VOWELS = "aeiou"
LETTERS = "abcdefghijklmnopqrstuvwxyz"

default_config = default_ilql_config().evolve(
    train=dict(
        seq_length=48,
        batch_size=64,
        epochs=100,
        total_steps=400,
        checkpoint_interval=100000,
        eval_interval=25,
        tracker=None,
        checkpoint_dir="ckpts/ilql_summarize_synthetic",
    ),
    model=dict(
        model_path="random",
        num_layers_unfrozen=-1,
        model_arch_type="seq2seq",
        model_extra_configs={
            "seq2seq": dict(
                d_model=128, n_layer=3, n_head=4, d_kv=32, d_ff=512,
                relative_attention_num_buckets=16,
            )
        },
    ),
    tokenizer=dict(tokenizer_path="byte", truncation_side="right"),
    optimizer=dict(name="adamw", kwargs=dict(lr=3.0e-4)),
    scheduler=dict(name="cosine_annealing", kwargs=dict(T_max=400, eta_min=3.0e-4)),
    method=dict(
        tau=0.7,
        steps_for_target_q_sync=5,
        two_qs=True,
        alpha=0.1,
        beta=1,
        # eval sweeps the shaping strength like the TL;DR run (swept
        # gen_kwargs route to the decode-loop logits processor)
        gen_kwargs=dict(max_new_tokens=6, top_k=10, temperature=0.9,
                        beta=[0, 2]),
    ),
)


def make_documents(n: int, n_keys: int = 4, seed: int = 0):
    """(document, gold_summary) pairs: the summary is the record keys."""
    rng = np.random.RandomState(seed)
    docs, golds = [], []
    for _ in range(n):
        keys = rng.choice(list(LETTERS[:12]), size=n_keys, replace=False)
        records = [
            f"{k}{rng.choice(list(VOWELS))}{rng.randint(10)}" for k in keys
        ]
        docs.append(" ".join(records))
        golds.append("".join(keys))
    return docs, golds


def rouge1_proxy(generated: str, gold: str) -> float:
    """Unigram F1 over characters (one letter = one token under the
    byte tokenizer), the summary-quality stand-in for ROUGE-1."""
    g = [c for c in generated if c.isalpha()]
    r = list(gold)
    if not g or not r:
        return 0.0
    overlap = 0
    rest = list(r)
    for c in g:
        if c in rest:
            rest.remove(c)
            overlap += 1
    p, rec = overlap / len(g), overlap / len(r)
    return 0.0 if p + rec == 0 else 2 * p * rec / (p + rec)


def main(hparams={}):
    config = TRLConfig.update(default_config.to_dict(), hparams)
    rng = np.random.RandomState(7)
    docs, golds = make_documents(256, seed=config.train.seed)
    gold_of = dict(zip(docs, golds))

    samples, rewards = [], []
    for doc, gold in zip(docs, golds):
        samples.append((doc, gold))
        rewards.append(1.0)
        corrupted = "".join(rng.choice(list(LETTERS), size=len(gold)))
        samples.append((doc, corrupted))
        rewards.append(-1.0)

    def metric_fn(samples: List[str], prompts=None, outputs=None, **kw):
        outs = outputs if outputs is not None else samples
        ps = prompts if prompts is not None else [""] * len(outs)
        scores = [
            rouge1_proxy(o, gold_of.get(p.strip(), ""))
            for p, o in zip(ps, outs)
        ]
        return {"rouge1_proxy": scores}

    return trlx_tpu.train(
        samples=samples,
        rewards=rewards,
        eval_prompts=docs[:64],
        metric_fn=metric_fn,
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
