"""Stage 2: pairwise reward model for TL;DR (parity:
/root/reference/examples/summarize_rlhf/reward_model/train_reward_model_gptj.py).

A scalar head over the SFT model trained with the pairwise ranking loss
-log sigmoid(r_chosen - r_rejected) on comparison data — built on the
same trlx_tpu stack (jit + mesh + optax) rather than torch, so it runs
on the same TPU slice as stages 1 and 3.
"""

from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

from trlx_tpu.data.configs import TokenizerConfig
from trlx_tpu.models.heads import apply_head, init_head
from trlx_tpu.models.hf import load_pretrained
from trlx_tpu.parallel import data_sharding, make_mesh, shard_params
from trlx_tpu.utils import logging
from trlx_tpu.utils.tokenizers import load_tokenizer

logger = logging.get_logger(__name__)


def rm_forward(lm, params, input_ids, attention_mask):
    """Reward = scalar head on the last real token's hidden state."""
    out = lm(params["base"], input_ids, attention_mask)
    last = jnp.maximum(attention_mask.sum(axis=1) - 1, 0)
    hidden = jnp.take_along_axis(
        out["hidden_states"], last[:, None, None], axis=1
    )[:, 0]
    return apply_head(params["rm_head"], hidden)[:, 0]


def pairwise_loss(lm, params, chosen, chosen_mask, rejected, rejected_mask):
    r_chosen = rm_forward(lm, params, chosen, chosen_mask)
    r_rejected = rm_forward(lm, params, rejected, rejected_mask)
    loss = -jnp.mean(jax.nn.log_sigmoid(r_chosen - r_rejected))
    acc = jnp.mean((r_chosen > r_rejected).astype(jnp.float32))
    return loss, {"loss": loss, "acc": acc}


def main(
    model_path: str = "ckpts/sft_summarize/best_checkpoint/hf_model",
    out_dir: str = "ckpts/reward_model",
    max_length: int = 550,
    batch_size: int = 8,
    total_steps: int = 5000,
    lr: float = 1e-5,
):
    from datasets import load_dataset

    mesh = make_mesh()
    tokenizer = load_tokenizer(TokenizerConfig(tokenizer_path=model_path))
    lm, base_params, _ = load_pretrained(model_path)
    rng = jax.random.PRNGKey(0)
    params = {
        "base": base_params,
        "rm_head": init_head(rng, lm.cfg.hidden_size, 1),
    }
    with mesh:
        params = shard_params(mesh, params)
        tx = optax.adamw(lr)
        opt_state = jax.jit(tx.init)(params)

    @jax.jit
    def train_step(params, opt_state, batch):
        (loss, stats), grads = jax.value_and_grad(
            lambda p: pairwise_loss(lm, p, *batch), has_aux=True
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, stats

    dataset = load_dataset("CarperAI/openai_summarize_comparisons")["train"]

    def encode(texts):
        enc = tokenizer(list(texts), truncation=True, padding="max_length",
                        max_length=max_length)
        return (np.asarray(enc["input_ids"], np.int32),
                np.asarray(enc["attention_mask"], np.int32))

    sharding = data_sharding(mesh)
    step = 0
    while step < total_steps:
        for start in range(0, len(dataset) - batch_size, batch_size):
            rows = dataset[start : start + batch_size]
            c_ids, c_mask = encode(p + s for p, s in zip(rows["prompt"], rows["chosen"]))
            r_ids, r_mask = encode(p + s for p, s in zip(rows["prompt"], rows["rejected"]))
            batch = tuple(
                jax.device_put(x, sharding) for x in (c_ids, c_mask, r_ids, r_mask)
            )
            with mesh:
                params, opt_state, stats = train_step(params, opt_state, batch)
            step += 1
            if step % 50 == 0:
                logger.info("step %d loss %.4f acc %.3f", step,
                            float(stats["loss"]), float(stats["acc"]))
            if step >= total_steps:
                break

    os.makedirs(out_dir, exist_ok=True)
    import orbax.checkpoint as ocp

    ocp.PyTreeCheckpointer().save(
        os.path.join(os.path.abspath(out_dir), "params"), jax.device_get(params),
        force=True,
    )
    logger.info("reward model saved to %s", out_dir)


if __name__ == "__main__":
    kwargs = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(**kwargs)
