"""Stage 1 of the TL;DR summarize RLHF pipeline: SFT on human-written
summaries (parity: /root/reference/examples/summarize_rlhf/ — the full
SFT -> reward model -> PPO pipeline behind the reference's published
TL;DR numbers, README.md:51-61)."""

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_sft_config

default_config = default_sft_config().evolve(
    train=dict(
        seq_length=550,
        batch_size=16,
        total_steps=8000,
        eval_interval=1000,
        checkpoint_interval=2000,
        checkpoint_dir="ckpts/sft_summarize",
        mesh={"dp": -1, "fsdp": 8, "tp": 1, "sp": 1},
        compute_dtype="bfloat16",
    ),
    model=dict(model_path="EleutherAI/gpt-j-6B"),
    tokenizer=dict(tokenizer_path="EleutherAI/gpt-j-6B", truncation_side="right"),
    optimizer=dict(kwargs=dict(lr=1e-5, betas=(0.9, 0.95), eps=1e-8, weight_decay=1e-6)),
    method=dict(gen_kwargs=dict(max_new_tokens=50, do_sample=False)),
)


def main(hparams={}):
    config = TRLConfig.update(default_config.to_dict(), hparams)

    from datasets import load_dataset

    dataset = load_dataset("CarperAI/openai_summarize_tldr")
    samples = [(x["prompt"], x["label"]) for x in dataset["train"]]
    eval_prompts = [x["prompt"] for x in dataset["valid"]][:256]

    return trlx_tpu.train(samples=samples, eval_prompts=eval_prompts, config=config)


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
