"""Stage 4: evaluate a trained TL;DR policy — ROUGE-1/2/L vs the human
summaries plus reward-model score over a test split.

Parity: /root/reference/examples/summarize_rlhf/trlx_inference_gptj.py
(generation + ROUGE table) and reward_model/gptj_reward_test.py (RM
score over the test set). Together with the README table these scripts
produce the reference's only published-metric baseline (BASELINE.md:
ROUGE-1/2/L/avg 0.334/0.125/0.261/0.240 for SFT, mean reward 2.729 SFT
-> 3.291 PPO), so this script emits the same schema.

ROUGE here is first-party (`rouge_scores` below: unigram/bigram F1 and
LCS F1 over whitespace-ish tokens, the same definition `evaluate`'s
default rouge uses) so the eval runs with zero network egress; if the
`evaluate` package has a cached rouge it is preferred.

Air-gapped smoke path: `SMOKE=1 python inference_eval.py` runs the full
mechanics (generation -> ROUGE -> table) on a tiny random-init model
with the byte tokenizer and synthetic posts — no checkpoints, no
network — exercising every line except real checkpoint loading.
"""

import json
import os
import re
import sys
from collections import Counter
from typing import Dict, List, Optional


# ---------------------------------------------------------------------------
# first-party ROUGE (zero-egress replacement for evaluate.load("rouge"))
# ---------------------------------------------------------------------------


def _tokens(text: str) -> List[str]:
    return re.findall(r"[a-z0-9]+", text.lower())


def _f1(match: int, pred: int, ref: int) -> float:
    if pred == 0 or ref == 0 or match == 0:
        return 0.0
    p, r = match / pred, match / ref
    return 2 * p * r / (p + r)


def _ngram_f1(pred: List[str], ref: List[str], n: int) -> float:
    pg = Counter(zip(*[pred[i:] for i in range(n)]))
    rg = Counter(zip(*[ref[i:] for i in range(n)]))
    match = sum((pg & rg).values())
    return _f1(match, max(sum(pg.values()), 0), max(sum(rg.values()), 0))


def _lcs_len(a: List[str], b: List[str]) -> int:
    if not a or not b:
        return 0
    prev = [0] * (len(b) + 1)
    for x in a:
        cur = [0]
        for j, y in enumerate(b, 1):
            cur.append(prev[j - 1] + 1 if x == y else max(prev[j], cur[-1]))
        prev = cur
    return prev[-1]


def rouge_scores(predictions: List[str], references: List[str]) -> Dict[str, float]:
    """Corpus-mean ROUGE-1/2/L F-measures."""
    r1 = r2 = rl = 0.0
    for pred_text, ref_text in zip(predictions, references):
        pred, ref = _tokens(pred_text), _tokens(ref_text)
        r1 += _ngram_f1(pred, ref, 1)
        r2 += _ngram_f1(pred, ref, 2)
        rl += _f1(_lcs_len(pred, ref), len(pred), len(ref))
    n = max(len(predictions), 1)
    return {"rouge1": r1 / n, "rouge2": r2 / n, "rougeL": rl / n}


def compute_rouge(predictions: List[str], references: List[str]) -> Dict[str, float]:
    try:  # prefer a locally cached `evaluate` rouge when present
        import evaluate

        r = evaluate.load("rouge").compute(
            predictions=predictions, references=references
        )
        return {k: float(r[k]) for k in ("rouge1", "rouge2", "rougeL")}
    except Exception:
        return rouge_scores(predictions, references)


# ---------------------------------------------------------------------------
# generation
# ---------------------------------------------------------------------------


def generate_summaries(
    lm, params, tokenizer, posts: List[str], max_prompt: int, max_new: int,
    batch_size: int = 16,
) -> List[str]:
    """Left-padded batched sampling of `max_new` tokens per post."""
    import jax
    import jax.numpy as jnp

    from trlx_tpu.models.generation import SamplerSettings, make_generate_fn

    settings = SamplerSettings(
        max_new_tokens=max_new,
        do_sample=False,
        eos_token_id=tokenizer.eos_token_id if tokenizer.eos_token_id is not None else -1,
        pad_token_id=tokenizer.pad_token_id or 0,
    )
    tokenizer.padding_side = "left"
    fn = make_generate_fn(lm, settings)
    rng = jax.random.PRNGKey(0)
    preds = []
    for i in range(0, len(posts), batch_size):
        chunk = posts[i : i + batch_size]
        pad_to = batch_size  # one compiled sampler for every chunk
        chunk = chunk + [chunk[-1]] * (pad_to - len(chunk))
        enc = tokenizer(
            chunk, truncation=True, padding="max_length", max_length=max_prompt
        )
        rng, sub = jax.random.split(rng)
        out = fn(
            params,
            jnp.asarray(enc["input_ids"], jnp.int32),
            jnp.asarray(enc["attention_mask"], jnp.int32),
            sub,
        )
        texts = tokenizer.batch_decode(
            [[t for t, m in zip(ids, mask) if m] for ids, mask in zip(
                out["response_ids"].tolist(), out["response_mask"].tolist()
            )]
        )
        preds.extend(texts[: len(posts[i : i + batch_size])])
    return preds


# ---------------------------------------------------------------------------
# table (BASELINE.md schema)
# ---------------------------------------------------------------------------


def emit_table(name: str, rouge: Dict[str, float], mean_reward: Optional[float]):
    avg = (rouge["rouge1"] + rouge["rouge2"] + rouge["rougeL"]) / 3
    print(f"| TL;DR ROUGE-1 / ROUGE-2 / ROUGE-L / avg ({name}) | "
          f"{rouge['rouge1']:.3f} / {rouge['rouge2']:.3f} / "
          f"{rouge['rougeL']:.3f} / {avg:.3f} |")
    if mean_reward is not None:
        print(f"| TL;DR summarization, mean reward ({name}) | {mean_reward:.3f} |")
    print(json.dumps({"model": name, **{k: round(v, 4) for k, v in rouge.items()},
                      "rouge_avg": round(avg, 4),
                      "mean_reward": None if mean_reward is None
                      else round(mean_reward, 4)}))


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def run_eval(model_dir: str, name: str, n_samples: int = 100):
    """Real path: HF-layout checkpoint + TL;DR test split + optional RM."""
    from datasets import load_dataset

    from trlx_tpu.data.configs import TokenizerConfig
    from trlx_tpu.models.hf import load_pretrained
    from trlx_tpu.utils.tokenizers import load_tokenizer

    lm, params, _ = load_pretrained(model_dir)
    tokenizer = load_tokenizer(TokenizerConfig(tokenizer_path=model_dir,
                                               truncation_side="left"))
    test = load_dataset("CarperAI/openai_summarize_tldr", split="test")
    posts = [x["prompt"] for x in test][:n_samples]
    refs = [x["label"] for x in test][:n_samples]

    preds = generate_summaries(lm, params, tokenizer, posts,
                               max_prompt=500, max_new=50)
    preds = [p.split("TL;DR:")[-1] for p in preds]
    rouge = compute_rouge(preds, refs)

    mean_reward = None
    rm_dir = os.environ.get("RM_DIR")
    if rm_dir:  # RM score of post+summary (gptj_reward_test.py analog)
        from examples.summarize_rlhf.ppo_summarize import make_rm_reward_fn

        rm_score = make_rm_reward_fn(rm_dir)
        scores = rm_score([p + " " + s for p, s in zip(posts, preds)])
        mean_reward = float(scores.mean())
    emit_table(name, rouge, mean_reward)


def run_smoke():
    """Air-gapped mechanics check: tiny random model, byte tokenizer,
    synthetic posts/references. Asserts the table emits and ROUGE is
    self-consistent (predicting the reference scores 1.0)."""
    import jax

    # force CPU before any backend initializes (must be jax.config, not
    # env: the image's sitecustomize pre-registers a TPU plugin)
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from trlx_tpu.models.transformer import TransformerConfig, TransformerLM
    from trlx_tpu.utils.tokenizers import ByteTokenizer

    cfg = TransformerConfig(
        vocab_size=260, hidden_size=32, n_layer=2, n_head=2, n_positions=128,
        dtype=jnp.float32,
    )
    lm = TransformerLM(cfg)
    params = lm.init(jax.random.PRNGKey(0))
    tokenizer = ByteTokenizer()

    posts = [f"post number {i} about a cat on a mat TL;DR:" for i in range(6)]
    refs = [f"cat {i} sits" for i in range(6)]
    preds = generate_summaries(lm, params, tokenizer, posts,
                               max_prompt=48, max_new=8, batch_size=4)
    assert len(preds) == len(posts)

    # the metric itself: identical strings score 1.0 across the board
    perfect = rouge_scores(refs, refs)
    assert all(abs(v - 1.0) < 1e-9 for v in perfect.values()), perfect
    rouge = compute_rouge(preds, refs)
    emit_table("smoke", rouge, mean_reward=None)
    print("smoke OK")


if __name__ == "__main__":
    if os.environ.get("SMOKE") == "1" or "--smoke" in sys.argv:
        run_smoke()
    else:
        model_dir = sys.argv[1] if len(sys.argv) > 1 else (
            "ckpts/ppo_summarize/best_checkpoint/hf_model"
        )
        name = sys.argv[2] if len(sys.argv) > 2 else "PPO"
        run_eval(model_dir, name, n_samples=int(os.environ.get("N_SAMPLES", "100")))
