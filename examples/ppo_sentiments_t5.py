"""PPO on T5 for IMDB review completion (parity:
/root/reference/examples/ppo_sentiments_t5.py — the seq2seq PPO path)."""

from typing import List

import trlx_tpu
from trlx_tpu.data.configs import (
    ModelConfig,
    OptimizerConfig,
    SchedulerConfig,
    TokenizerConfig,
    TrainConfig,
    TRLConfig,
)
from trlx_tpu.data.method_configs import PPOConfig

default_config = TRLConfig(
    train=TrainConfig(
        seq_length=128,
        epochs=100,
        total_steps=100000,
        batch_size=12,
        checkpoint_interval=10000,
        eval_interval=100,
        pipeline="PromptPipeline",
        trainer="TPUPPOTrainer",
        save_best=False,
        checkpoint_dir="ckpts/ppo_sentiments_t5",
    ),
    model=ModelConfig(
        model_path="lvwerra/t5-imdb", num_layers_unfrozen=-1, model_arch_type="seq2seq"
    ),
    tokenizer=TokenizerConfig(
        tokenizer_path="lvwerra/t5-imdb", padding_side="right", truncation_side="right"
    ),
    optimizer=OptimizerConfig(
        name="adamw", kwargs=dict(lr=5.0e-5, betas=(0.9, 0.999), eps=1.0e-8, weight_decay=1.0e-6)
    ),
    scheduler=SchedulerConfig(name="cosine_annealing", kwargs=dict(T_max=10000, eta_min=5.0e-5)),
    method=PPOConfig(
        name="PPOConfig",
        num_rollouts=128,
        chunk_size=12,
        ppo_epochs=4,
        init_kl_coef=0.05,
        target=6,
        horizon=10000,
        gamma=0.99,
        lam=0.95,
        cliprange=0.2,
        cliprange_value=0.2,
        vf_coef=1.0,
        scale_reward=None,
        ref_mean=None,
        ref_std=None,
        cliprange_reward=10,
        gen_kwargs=dict(max_new_tokens=64, do_sample=True, top_k=0, top_p=1.0),
    ),
)


def get_positive_score(scores) -> float:
    return dict(map(lambda x: tuple(x.values()), scores))["POSITIVE"]


def main(hparams={}):
    config = TRLConfig.update(default_config.to_dict(), hparams)

    from datasets import load_dataset
    from transformers import pipeline as hf_pipeline

    sentiment_fn = hf_pipeline(
        "sentiment-analysis", "lvwerra/distilbert-imdb", top_k=2,
        truncation=True, batch_size=256,
    )

    def reward_fn(samples: List[str], **kwargs) -> List[float]:
        return list(map(get_positive_score, sentiment_fn(samples)))

    imdb = load_dataset("imdb", split="train+test")
    prompts = [" ".join(review.split()[:4]) for review in imdb["text"]]

    return trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=["I don't know much about Hungarian underground"] * 64,
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
