"""Instruction SFT on Alpaca (parity:
/root/reference/examples/alpaca/sft_alpaca.py)."""

from typing import Dict, List

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_sft_config


def preprocess(instruction: str, input_text: str, output: str):
    """Alpaca prompt template -> (prompt, output) pair."""
    if input_text:
        prefix = (
            "Below is an instruction that describes a task, paired with an input "
            "that provides further context. Write a response that appropriately "
            f"completes the request.\n\n### Instruction:\n{instruction}\n\n"
            f"### Input:\n{input_text}\n\n### Response:\n"
        )
    else:
        prefix = (
            "Below is an instruction that describes a task. Write a response "
            "that appropriately completes the request.\n\n### Instruction:\n"
            f"{instruction}\n\n### Response:\n"
        )
    return (prefix, output)


def main(hparams={}):
    config = TRLConfig.update(
        default_sft_config().evolve(
            train=dict(total_steps=2400, batch_size=16, seq_length=512,
                       checkpoint_dir="ckpts/sft_alpaca"),
        ).to_dict(),
        hparams,
    )

    from datasets import load_dataset

    alpaca = load_dataset("tatsu-lab/alpaca", split="train")
    samples = [
        preprocess(x["instruction"], x["input"], x["output"]) for x in alpaca
    ]

    return trlx_tpu.train(
        samples=samples,
        eval_prompts=[preprocess("Tell me a joke.", "", "")[0]] * 32,
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
