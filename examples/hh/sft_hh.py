"""SFT GPT-J-6B on Anthropic HH chosen responses (parity:
/root/reference/examples/hh/sft_hh.py)."""

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_sft_config

default_config = default_sft_config().evolve(
    train=dict(
        seq_length=1024,
        batch_size=32,
        total_steps=8000,
        checkpoint_interval=10000,
        eval_interval=1000,
        checkpoint_dir="ckpts/sft_hh",
        mesh={"dp": -1, "fsdp": 8, "tp": 1, "sp": 1},
        compute_dtype="bfloat16",
    ),
    model=dict(model_path="EleutherAI/gpt-j-6B"),
    tokenizer=dict(tokenizer_path="EleutherAI/gpt-j-6B", truncation_side="left"),
    method=dict(gen_kwargs=dict(max_new_tokens=128, top_k=20, temperature=1.0)),
)


def preprocess(sample):
    sample["prompt"] += "Assistant:"
    return sample


def main(hparams={}):
    config = TRLConfig.update(default_config.to_dict(), hparams)

    from datasets import load_dataset

    dataset = load_dataset("Dahoas/full-hh-rlhf").map(preprocess)
    samples = [(x["prompt"], x["chosen"]) for x in dataset["train"]]
    eval_prompts = [{"prompt": x["prompt"]} for x in dataset["test"]][:280]

    return trlx_tpu.train(
        samples=samples,
        eval_prompts=eval_prompts,
        config=config,
        stop_sequences=["Human:", "human:", "Assistant:", "assistant:"],
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
