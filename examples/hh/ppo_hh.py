"""PPO GPT-J-6B on Anthropic HH (parity:
/root/reference/examples/hh/ppo_hh.py). The reward model is served
remotely — the reference uses a Triton gRPC client; here the client is
transport-agnostic (HTTP JSON via HH_RM_URL, or an in-process HF reward
model via HH_RM_PATH) since reward serving is host-side I/O, not TPU
compute (SURVEY.md §2.8 last row).

Scale preset: GPT-J-class on a v4-8 with fsdp=4 x tp=2 — the AOT memory
fit (__graft_entry__.dryrun_scale, row 6b_v4_fsdp4_tp2) shows ~24.4 GB
peak per 32 GB chip (~24% headroom; the pure-fsdp8 layout fits at <7%,
too tight once real-run HBM fragmentation eats ~2 GB). Counterpart of
the reference's 7-train-GPU + 1-RM-GPU layout.
"""

import os
from typing import List

import trlx_tpu
from trlx_tpu.data.configs import (
    ModelConfig,
    OptimizerConfig,
    SchedulerConfig,
    TokenizerConfig,
    TrainConfig,
    TRLConfig,
)
from trlx_tpu.data.method_configs import PPOConfig

default_config = TRLConfig(
    train=TrainConfig(
        seq_length=1024,
        epochs=10000,
        total_steps=10000,
        batch_size=32,
        checkpoint_interval=10000,
        eval_interval=500,
        pipeline="PromptPipeline",
        trainer="TPUPPOTrainer",
        checkpoint_dir="ckpts/ppo_hh",
        mesh={"dp": -1, "fsdp": 4, "tp": 2, "sp": 1},
        compute_dtype="bfloat16",
    ),
    model=ModelConfig(model_path="EleutherAI/gpt-j-6B", num_layers_unfrozen=2),
    tokenizer=TokenizerConfig(tokenizer_path="EleutherAI/gpt-j-6B", truncation_side="left"),
    optimizer=OptimizerConfig(
        name="adamw", kwargs=dict(lr=8e-6, betas=(0.9, 0.95), eps=1.0e-8, weight_decay=1.0e-6)
    ),
    scheduler=SchedulerConfig(name="cosine_annealing", kwargs=dict(T_max=10000, eta_min=8e-6)),
    method=PPOConfig(
        name="PPOConfig",
        num_rollouts=64,
        chunk_size=16,
        ppo_epochs=4,
        init_kl_coef=0.05,
        target=6,
        horizon=10000,
        gamma=1,
        lam=0.95,
        cliprange=0.2,
        cliprange_value=0.2,
        vf_coef=1,
        scale_reward="running",
        ref_mean=None,
        ref_std=None,
        cliprange_reward=10,
        gen_kwargs=dict(max_new_tokens=128, top_k=0, top_p=1.0, do_sample=True),
    ),
)


def make_reward_fn():
    """Remote (HTTP JSON) or local (HF torch) HH reward model."""
    rm_url = os.environ.get("HH_RM_URL")
    if rm_url:
        import json
        import urllib.request

        def reward_fn(samples: List[str], **kwargs) -> List[float]:
            req = urllib.request.Request(
                rm_url,
                data=json.dumps({"samples": samples}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=300) as resp:
                return json.load(resp)["rewards"]

        return reward_fn

    rm_path = os.environ.get("HH_RM_PATH", "Dahoas/gptj-rm-static")
    import torch
    from transformers import AutoModelForSequenceClassification, AutoTokenizer

    rm_tokenizer = AutoTokenizer.from_pretrained(rm_path)
    rm = AutoModelForSequenceClassification.from_pretrained(rm_path)
    rm.eval()

    @torch.no_grad()
    def reward_fn(samples: List[str], **kwargs) -> List[float]:
        out = []
        for i in range(0, len(samples), 8):
            enc = rm_tokenizer(
                samples[i : i + 8], truncation=True, max_length=1024,
                padding=True, return_tensors="pt",
            )
            out.extend(rm(**enc).logits[:, 0].tolist())
        return out

    return reward_fn


def preprocess(sample):
    sample["prompt"] += "Assistant:"
    return sample


def main(hparams={}):
    config = TRLConfig.update(default_config.to_dict(), hparams)

    from datasets import load_dataset

    dataset = load_dataset("Dahoas/rm-static").map(preprocess)
    prompts = [{"prompt": x["prompt"]} for x in dataset["train"]]
    eval_prompts = [{"prompt": x["prompt"]} for x in dataset["test"]][:280]

    return trlx_tpu.train(
        reward_fn=make_reward_fn(),
        prompts=prompts,
        eval_prompts=eval_prompts,
        config=config,
        stop_sequences=["Human:", "human:", "Assistant:", "assistant:"],
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
