"""ILQL GPT-J-6B on Anthropic HH (parity:
/root/reference/examples/hh/ilql_hh.py): offline training on
chosen/rejected pairs with +1/-1 rewards."""

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_ilql_config

default_config = default_ilql_config().evolve(
    train=dict(
        seq_length=1024,
        batch_size=32,
        total_steps=20000,
        checkpoint_interval=10000,
        eval_interval=1000,
        checkpoint_dir="ckpts/ilql_hh",
        mesh={"dp": -1, "fsdp": 8, "tp": 1, "sp": 1},
        compute_dtype="bfloat16",
    ),
    model=dict(model_path="EleutherAI/gpt-j-6B"),
    tokenizer=dict(tokenizer_path="EleutherAI/gpt-j-6B", truncation_side="left"),
    method=dict(
        gen_kwargs=dict(max_new_tokens=128, top_k=20, beta=[1, 4], temperature=1.0)
    ),
)


def preprocess(sample):
    sample["prompt"] += "Assistant:"
    return sample


def main(hparams={}):
    config = TRLConfig.update(default_config.to_dict(), hparams)

    from datasets import load_dataset

    dataset = load_dataset("Dahoas/full-hh-rlhf").map(preprocess)
    samples, rewards = [], []
    for x in dataset["train"]:
        samples += [(x["prompt"], x["chosen"]), (x["prompt"], x["rejected"])]
        rewards += [1.0, -1.0]
    eval_prompts = [{"prompt": x["prompt"]} for x in dataset["test"]][:280]

    return trlx_tpu.train(
        samples=samples,
        rewards=rewards,
        eval_prompts=eval_prompts,
        config=config,
        stop_sequences=["Human:", "human:", "Assistant:", "assistant:"],
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
