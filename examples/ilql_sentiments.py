"""ILQL GPT2 on IMDB sentiment (parity:
/root/reference/examples/ilql_sentiments.py): offline training on raw
reviews labeled by a sentiment classifier."""

from typing import Dict, List

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_ilql_config


def get_positive_score(scores: List[Dict[str, float]]) -> float:
    return dict(map(lambda x: tuple(x.values()), scores))["POSITIVE"]


def main(hparams={}):
    config = TRLConfig.update(default_ilql_config().to_dict(), hparams)

    from datasets import load_dataset
    from transformers import pipeline as hf_pipeline

    sentiment_fn = hf_pipeline(
        "sentiment-analysis",
        "lvwerra/distilbert-imdb",
        top_k=2,
        truncation=True,
        batch_size=256,
    )

    def metric_fn(samples: List[str], **kwargs) -> Dict[str, List[float]]:
        return {"sentiments": list(map(get_positive_score, sentiment_fn(samples)))}

    imdb = load_dataset("imdb", split="train+test")

    return trlx_tpu.train(
        samples=imdb["text"],
        rewards=metric_fn(imdb["text"])["sentiments"],
        eval_prompts=["I don't know much about Hungarian underground"] * 256,
        metric_fn=metric_fn,
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
