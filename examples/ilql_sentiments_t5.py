"""ILQL on T5 for IMDB sentiment (parity:
/root/reference/examples/ilql_sentiments_t5.py — the seq2seq offline
path)."""

from typing import Dict, List

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_ilql_config

default_config = default_ilql_config().evolve(
    train=dict(
        batch_size=32, seq_length=128, checkpoint_dir="ckpts/ilql_sentiments_t5"
    ),
    model=dict(model_path="lvwerra/t5-imdb", model_arch_type="seq2seq"),
    tokenizer=dict(tokenizer_path="lvwerra/t5-imdb", padding_side="right"),
    method=dict(gen_kwargs=dict(max_new_tokens=56, top_k=20, beta=[1, 2], temperature=1.0)),
)


def get_positive_score(scores) -> float:
    return dict(map(lambda x: tuple(x.values()), scores))["POSITIVE"]


def main(hparams={}):
    config = TRLConfig.update(default_config.to_dict(), hparams)

    from datasets import load_dataset
    from transformers import pipeline as hf_pipeline

    sentiment_fn = hf_pipeline(
        "sentiment-analysis", "lvwerra/distilbert-imdb", top_k=2,
        truncation=True, batch_size=256,
    )

    def metric_fn(samples: List[str], **kwargs) -> Dict[str, List[float]]:
        return {"sentiments": list(map(get_positive_score, sentiment_fn(samples)))}

    imdb = load_dataset("imdb", split="train+test")
    # split each review into a (prompt, continuation) pair for the
    # encoder/decoder sides
    samples = [
        (" ".join(text.split()[:4]), " ".join(text.split()[4:64]))
        for text in imdb["text"]
    ]
    rewards = metric_fn([p + " " + o for p, o in samples])["sentiments"]

    return trlx_tpu.train(
        samples=samples,
        rewards=rewards,
        eval_prompts=["I don't know much about Hungarian underground"] * 64,
        metric_fn=metric_fn,
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    main({} if len(sys.argv) == 1 else json.loads(sys.argv[1]))
