"""PPO T5 summarization on CNN/DailyMail with a ROUGE reward (parity:
/root/reference/examples/summarize_daily_cnn/t5_summarize_daily_cnn.py)."""

from typing import List

import trlx_tpu
from trlx_tpu.data.configs import (
    ModelConfig,
    OptimizerConfig,
    SchedulerConfig,
    TokenizerConfig,
    TrainConfig,
    TRLConfig,
)
from trlx_tpu.data.method_configs import PPOConfig

default_config = TRLConfig(
    train=TrainConfig(
        seq_length=612,
        epochs=100,
        total_steps=100000,
        batch_size=12,
        checkpoint_interval=10000,
        eval_interval=500,
        pipeline="PromptPipeline",
        trainer="TPUPPOTrainer",
        checkpoint_dir="ckpts/t5_summarize",
    ),
    model=ModelConfig(
        model_path="google/flan-t5-large", model_arch_type="seq2seq",
        num_layers_unfrozen=2,
    ),
    tokenizer=TokenizerConfig(
        tokenizer_path="google/flan-t5-large", padding_side="right",
        truncation_side="right",
    ),
    optimizer=OptimizerConfig(
        name="adamw", kwargs=dict(lr=1.0e-5, betas=(0.9, 0.999), eps=1.0e-8, weight_decay=1.0e-6)
    ),
    scheduler=SchedulerConfig(name="cosine_annealing", kwargs=dict(T_max=10000, eta_min=1.0e-6)),
    method=PPOConfig(
        name="PPOConfig",
        num_rollouts=512,
        chunk_size=12,
        ppo_epochs=4,
        init_kl_coef=0.05,
        target=6,
        horizon=10000,
        gamma=0.99,
        lam=0.95,
        cliprange=0.2,
        cliprange_value=0.2,
        vf_coef=1.0,
        scale_reward=None,
        ref_mean=None,
        ref_std=None,
        cliprange_reward=10,
        gen_kwargs=dict(max_new_tokens=100, do_sample=True, top_k=0, top_p=1.0),
    ),
)


def main(hparams={}):
    config = TRLConfig.update(default_config.to_dict(), hparams)

    import evaluate
    from datasets import load_dataset

    rouge = evaluate.load("rouge")
    dataset = load_dataset("cnn_dailymail", "3.0.0")
    prompt_summary = {
        ("Summarize: " + x["article"])[:2000]: x["highlights"]
        for split in ("train", "validation")
        for x in dataset[split]
    }

    def reward_fn(samples: List[str], prompts: List[str], outputs: List[str], **kwargs):
        refs = [prompt_summary.get(p, "") for p in prompts]
        scores = rouge.compute(
            predictions=outputs, references=refs, use_aggregator=False
        )["rouge1"]
        return list(scores)

    prompts = list(prompt_summary)[:20000]
    eval_prompts = list(prompt_summary)[20000:20256]

    return trlx_tpu.train(
        reward_fn=reward_fn, prompts=prompts, eval_prompts=eval_prompts, config=config
    )


if __name__ == "__main__":
    import json
    import sys

    main({} if len(sys.argv) == 1 else json.loads(sys.argv[1]))
