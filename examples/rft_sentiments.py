"""RFT GPT2 on IMDB sentiment (parity:
/root/reference/examples/rft_sentiments.py): iterated rejection-sampling
fine-tuning toward positive continuations."""

from typing import Dict, List

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_rft_config


def get_positive_score(scores: List[Dict[str, float]]) -> float:
    return dict(map(lambda x: tuple(x.values()), scores))["POSITIVE"]


def main(hparams={}):
    config = TRLConfig.update(default_rft_config().to_dict(), hparams)

    from datasets import load_dataset
    from transformers import pipeline as hf_pipeline

    sentiment_fn = hf_pipeline(
        "sentiment-analysis",
        "lvwerra/distilbert-imdb",
        top_k=2,
        truncation=True,
        batch_size=256,
    )

    def reward_fn(samples: List[str], **kwargs) -> List[float]:
        return list(map(get_positive_score, sentiment_fn(samples)))

    imdb = load_dataset("imdb", split="train+test")
    prompts = [" ".join(review.split()[:4]) for review in imdb["text"]][:128]

    return trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=["I don't know much about Hungarian underground"] * 64,
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
