"""ILQL on prompt/aesthetic-rating pairs from simulacra-aesthetic-captions
(parity: /root/reference/examples/simulacra.py)."""

import os
import sqlite3
from urllib.request import urlretrieve

import trlx_tpu
from trlx_tpu.data.default_configs import default_ilql_config

URL = (
    "https://raw.githubusercontent.com/JD-P/simulacra-aesthetic-captions/"
    "main/sac_public_2022_06_29.sqlite"
)
DBPATH = "sac_public_2022_06_29.sqlite"


def main():
    if not os.path.exists(DBPATH):
        print(f"fetching {DBPATH}")
        urlretrieve(URL, DBPATH)

    conn = sqlite3.connect(DBPATH)
    c = conn.cursor()
    c.execute(
        "SELECT prompt, rating FROM ratings "
        "JOIN images ON images.id=ratings.iid "
        "JOIN generations ON images.gid=generations.id "
        "WHERE rating IS NOT NULL;"
    )
    prompts, ratings = tuple(map(list, zip(*c.fetchall())))
    return trlx_tpu.train(
        config=default_ilql_config(),
        samples=prompts,
        rewards=ratings,
        eval_prompts=["An astronaut riding a horse"] * 64,
    )


if __name__ == "__main__":
    main()
