"""Toy list-manipulation DSL + interpreter for grounded program synthesis
(parity: /root/reference/examples/experiments/grounded_program_synthesis/lang.py
— same task: given an input list and a target output, the model writes a
DSL program; the reward grounds generated programs in the interpreter).

The implementation is first-party: a recursive-descent parser over the
`fn(arg, ...)` call syntax instead of the reference's token-template
interpreter, and a depth-bounded random program sampler for the
synthetic dataset.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Tuple

CONSTANTS = [-5, -4, -3, -2, -1, 1, 2, 3, 4, 5]

DSL: Dict[str, Tuple[Callable, int]] = {
    # name -> (fn, arity) where arity counts (list, int?) arguments
    "take": (lambda xs, n: xs[:n], 2),
    "drop": (lambda xs, n: xs[n:], 2),
    "reverse": (lambda xs: xs[::-1], 1),
    "sort_asc": (lambda xs: sorted(xs), 1),
    "sort_des": (lambda xs: sorted(xs, reverse=True), 1),
    "add_n": (lambda xs, n: [x + n for x in xs], 2),
    "sub_n": (lambda xs, n: [x - n for x in xs], 2),
    "mul_n": (lambda xs, n: [x * n for x in xs], 2),
    "expand_copy": (lambda xs: xs + xs, 1),
}


class Interpreter:
    """Evaluate programs like `add_n(reverse(x), 2)` against input `x`."""

    def __call__(self, program: str, x: List[int]) -> Any:
        self.text = program.strip()
        self.pos = 0
        self.x = x
        try:
            result = self._expr()
            if self.pos != len(self.text):
                return "ERROR"
            return result
        except Exception:
            return "ERROR"

    def _expr(self):
        self._ws()
        if self.text[self.pos] in "-0123456789":
            start = self.pos
            self.pos += 1
            while self.pos < len(self.text) and self.text[self.pos].isdigit():
                self.pos += 1
            return int(self.text[start : self.pos])
        start = self.pos
        while self.pos < len(self.text) and (
            self.text[self.pos].isalnum() or self.text[self.pos] == "_"
        ):
            self.pos += 1
        name = self.text[start : self.pos]
        if name == "x":
            return list(self.x)
        if name not in DSL:
            raise ValueError(name)
        fn, arity = DSL[name]
        self._consume("(")
        args = [self._expr()]
        for _ in range(arity - 1):
            self._consume(",")
            args.append(self._expr())
        self._consume(")")
        return fn(*args)

    def _ws(self):
        while self.pos < len(self.text) and self.text[self.pos] == " ":
            self.pos += 1

    def _consume(self, ch: str):
        self._ws()
        if self.text[self.pos] != ch:
            raise ValueError(f"expected {ch!r}")
        self.pos += 1


interpreter = Interpreter()


def random_program(rng: random.Random, depth: int = 2) -> str:
    """Sample a random composition of DSL calls applied to `x`."""
    expr = "x"
    for _ in range(rng.randint(1, depth)):
        name = rng.choice(list(DSL))
        _, arity = DSL[name]
        if arity == 1:
            expr = f"{name}({expr})"
        else:
            expr = f"{name}({expr},{rng.choice(CONSTANTS)})"
    return expr


def random_input(rng: random.Random, max_len: int = 5, value: int = 5) -> List[int]:
    return [rng.randint(-value, value) for _ in range(rng.randint(2, max_len))]


def create_synthetic_dataset(size: int, seed: int = 0) -> List[dict]:
    """[{input, output, program}] with prompts in the reference's
    'Input: ... Output: ... Function:' grounding format."""
    rng = random.Random(seed)
    out = []
    while len(out) < size:
        program = random_program(rng)
        x = random_input(rng)
        y = interpreter(program, x)
        if y == "ERROR" or y == [] or y == x:
            continue
        out.append(
            {
                "input": x,
                "output": y,
                "program": program,
                "prompt": f"Input: {x} Output: {y} Function:",
                "completion": f" {program}",
            }
        )
    return out


def reward_fn(samples: List[str], prompts: List[str], outputs: List[str], **kwargs) -> List[float]:
    """+1 exact functional match, partial credit for list overlap, -1 for
    uninterpretable programs (grounding, parity with the reference's
    reward shape)."""
    rewards = []
    for prompt, output in zip(prompts, outputs):
        try:
            x = eval(prompt.split("Input:")[1].split("Output:")[0].strip())
            y = eval(prompt.split("Output:")[1].split("Function:")[0].strip())
        except Exception:
            rewards.append(-1.0)
            continue
        pred = interpreter(output.strip(), x)
        if pred == "ERROR":
            rewards.append(-1.0)
        elif pred == y:
            rewards.append(1.0)
        elif isinstance(pred, list) and isinstance(y, list) and pred:
            common = sum(1 for a, b in zip(pred, y) if a == b)
            rewards.append(common / max(len(y), len(pred)) - 0.5)
        else:
            rewards.append(-0.5)
    return rewards
