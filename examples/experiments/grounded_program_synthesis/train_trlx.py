"""PPO on the toy DSL program synthesis task (parity:
/root/reference/examples/experiments/grounded_program_synthesis/train_trlx.py).
Runs air-gapped: byte tokenizer + random-init model, with an SFT warmup
on the synthetic dataset (standing in for the reference's pretrained
codegen checkpoint)."""

import trlx_tpu
from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.data.default_configs import default_ppo_config

from examples.experiments.grounded_program_synthesis.lang import (
    create_synthetic_dataset,
    reward_fn,
)

default_config = default_ppo_config().evolve(
    train=dict(
        seq_length=128,
        batch_size=32,
        epochs=100,
        total_steps=2000,
        checkpoint_dir="ckpts/program_synthesis",
    ),
    model=dict(
        model_path="random",
        num_layers_unfrozen=-1,
        model_extra_configs={
            "transformer": dict(hidden_size=192, n_layer=6, n_head=6, n_positions=256)
        },
    ),
    tokenizer=dict(tokenizer_path="byte", truncation_side="right"),
    method=dict(
        num_rollouts=32, chunk_size=32,
        gen_kwargs=dict(max_new_tokens=48, top_k=0, top_p=1.0, do_sample=True),
    ),
)


def main(hparams={}):
    config = TRLConfig.update(default_config.to_dict(), hparams)
    dataset = create_synthetic_dataset(2000, seed=config.train.seed)

    # SFT warmup on (prompt, program) pairs, then PPO against the interpreter
    import os

    from trlx_tpu.data.method_configs import SFTConfig

    sft_dir = os.path.join(config.train.checkpoint_dir, "sft_warmup")
    model_dir = os.path.join(sft_dir, "hf_model")
    if not os.path.exists(os.path.join(model_dir, "trlx_tpu_config.json")):
        sft_config = TRLConfig.from_dict(
            dict(config.to_dict(), method=SFTConfig(name="sftconfig").to_dict())
        ).evolve(
            train=dict(trainer="TPUSFTTrainer", total_steps=500, epochs=20,
                       eval_interval=1000, checkpoint_interval=1000,
                       checkpoint_dir=sft_dir),
        )
        trainer = trlx_tpu.train(
            samples=[(d["prompt"], d["completion"]) for d in dataset],
            config=sft_config,
        )
        trainer.save_pretrained(model_dir)
    config.model.model_path = model_dir

    return trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=[d["prompt"] for d in dataset],
        eval_prompts=[d["prompt"] for d in dataset[:64]],
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    main({} if len(sys.argv) == 1 else json.loads(sys.argv[1]))
