"""PPO Llama-2-7B on IMDB sentiment continuation (parity:
/root/reference/examples/ppo_sentiments_llama.py). The llama mapping
(models/hf.py: rmsnorm + rotary + SwiGLU, untied head) plus the frozen
top-2-layer hydra reference, on a tp+fsdp mesh sized for a 7B policy.
Requires HF hub access; for an air-gapped llama-architecture smoke test,
set model_path="random" with a "transformer" dict using
norm="rmsnorm", pos_embed="rotary", mlp_gated=True.
"""

from typing import Dict, List

import trlx_tpu
from trlx_tpu.data.configs import (
    ModelConfig,
    OptimizerConfig,
    SchedulerConfig,
    TokenizerConfig,
    TrainConfig,
    TRLConfig,
)
from trlx_tpu.data.method_configs import PPOConfig


def get_positive_score(scores: List[Dict[str, float]]) -> float:
    return dict(map(lambda x: tuple(x.values()), scores))["POSITIVE"]


def llama_config() -> TRLConfig:
    return TRLConfig(
        train=TrainConfig(
            seq_length=1024,
            epochs=100,
            total_steps=400,
            batch_size=32,
            checkpoint_interval=10000,
            eval_interval=100,
            pipeline="PromptPipeline",
            trainer="TPUPPOTrainer",
            save_best=False,
            # 7B on a pod slice: shard params over fsdp, attention heads
            # over tp; dp absorbs the rest
            mesh={"dp": -1, "fsdp": 4, "tp": 2},
            compute_dtype="bfloat16",
        ),
        model=ModelConfig(
            model_path="NousResearch/Llama-2-7b-hf", num_layers_unfrozen=2
        ),
        tokenizer=TokenizerConfig(
            tokenizer_path="NousResearch/Llama-2-7b-hf", truncation_side="right"
        ),
        optimizer=OptimizerConfig(
            name="adamw",
            kwargs=dict(lr=1e-5, betas=(0.9, 0.95), eps=1.0e-8, weight_decay=1.0e-6),
        ),
        scheduler=SchedulerConfig(
            name="cosine_annealing", kwargs=dict(T_max=10000, eta_min=1.0e-5)
        ),
        method=PPOConfig(
            name="PPOConfig",
            num_rollouts=128,
            chunk_size=128,
            ppo_epochs=4,
            init_kl_coef=0.001,
            target=6,
            horizon=10000,
            gamma=1,
            lam=0.95,
            cliprange=0.2,
            cliprange_value=0.2,
            vf_coef=1,
            scale_reward="ignored",
            ref_mean=None,
            ref_std=None,
            cliprange_reward=10,
            gen_kwargs=dict(max_new_tokens=40, top_k=0, top_p=1.0, do_sample=True),
        ),
    )


def main(hparams={}):
    config = TRLConfig.update(llama_config().to_dict(), hparams)

    from datasets import load_dataset
    from transformers import pipeline as hf_pipeline

    sentiment_fn = hf_pipeline(
        "sentiment-analysis",
        "lvwerra/distilbert-imdb",
        top_k=2,
        truncation=True,
        batch_size=256,
    )

    def reward_fn(samples: List[str], **kwargs) -> List[float]:
        return list(map(get_positive_score, sentiment_fn(samples)))

    imdb = load_dataset("imdb", split="train+test")
    prompts = [" ".join(review.split()[:4]) for review in imdb["text"]]

    return trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=["I don't know much about Hungarian underground"] * 64,
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
