"""PPO Llama-2-7B on IMDB sentiment continuation (parity:
/root/reference/examples/ppo_sentiments_llama.py). Exercises the llama
mapping (models/hf.py: rmsnorm + rotary + SwiGLU, untied head) with the
frozen top-2-layer hydra reference, on a tp+fsdp mesh sized for a 7B
policy. Requires HF hub access; for an air-gapped llama-architecture
smoke test see tests/test_peft.py::test_ppo_llama_arch_with_lora
(random weights, same architecture switches).
"""

from typing import Dict, List

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_ppo_config

LLAMA = "NousResearch/Llama-2-7b-hf"


def llama_config() -> TRLConfig:
    return default_ppo_config().evolve(
        train=dict(
            total_steps=400,
            save_best=False,
            tracker="tensorboard",
            # 7B policy: params/opt-state sharded over fsdp, attention
            # heads over tp; dp absorbs the remaining chips
            mesh={"dp": -1, "fsdp": 4, "tp": 2},
            compute_dtype="bfloat16",
        ),
        model=dict(model_path=LLAMA, num_layers_unfrozen=2),
        tokenizer=dict(tokenizer_path=LLAMA, truncation_side="right"),
        optimizer=dict(
            name="adamw",
            kwargs=dict(lr=1e-5, betas=(0.9, 0.95), eps=1e-8, weight_decay=1e-6),
        ),
        scheduler=dict(
            name="cosine_annealing", kwargs=dict(T_max=10000, eta_min=1e-5)
        ),
        # adaptive KL: init_kl_coef=0.001 is the default; target=6 turns the
        # fixed controller into AdaptiveKLController(0.001, 6, 10000)
        method=dict(target=6),
    )


def positive_score(scores: List[Dict[str, float]]) -> float:
    return dict(map(lambda x: tuple(x.values()), scores))["POSITIVE"]


def main(hparams={}):
    config = TRLConfig.update(llama_config().to_dict(), hparams)

    from datasets import load_dataset
    from transformers import pipeline as hf_pipeline

    sentiment_fn = hf_pipeline(
        "sentiment-analysis",
        "lvwerra/distilbert-imdb",
        top_k=2,
        truncation=True,
        batch_size=256,
    )

    def reward_fn(samples: List[str], **kwargs) -> List[float]:
        return [positive_score(s) for s in sentiment_fn(samples)]

    imdb = load_dataset("imdb", split="train+test")
    prompts = [" ".join(review.split()[:4]) for review in imdb["text"]]

    return trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts,
        eval_prompts=["I don't know much about Hungarian underground"] * 64,
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    main({} if len(sys.argv) == 1 else json.loads(sys.argv[1]))
