"""PPO T5 on WMT en->de translation (parity:
/root/reference/examples/ppo_translation_t5.py). The reference optimizes
COMET with BLEU/chrF side metrics via `evaluate`/`unbabel-comet`; those
models need hub access, so the reward here is pluggable: COMET when the
packages are importable, otherwise a chrF-style character n-gram F-score
against the references computed locally (same reward shape, zero deps).
"""

from collections import Counter
from typing import List

import trlx_tpu
from trlx_tpu.data.configs import (
    ModelConfig,
    OptimizerConfig,
    SchedulerConfig,
    TokenizerConfig,
    TrainConfig,
    TRLConfig,
)
from trlx_tpu.data.method_configs import PPOConfig


def default_config() -> TRLConfig:
    return TRLConfig(
        train=TrainConfig(
            seq_length=612,
            epochs=100,
            total_steps=100000,
            batch_size=12,
            checkpoint_interval=10000,
            eval_interval=200,
            pipeline="PromptPipeline",
            trainer="TPUPPOTrainer",
        ),
        model=ModelConfig(
            model_path="t5-large", model_arch_type="seq2seq", num_layers_unfrozen=-1
        ),
        tokenizer=TokenizerConfig(
            tokenizer_path="t5-large", padding_side="right", truncation_side="right"
        ),
        optimizer=OptimizerConfig(
            name="adamw",
            kwargs={"lr": 2.0e-6, "betas": [0.9, 0.999], "eps": 1.0e-8,
                    "weight_decay": 1.0e-6},
        ),
        scheduler=SchedulerConfig(
            name="cosine_annealing", kwargs={"T_max": 10000, "eta_min": 1.0e-6}
        ),
        method=PPOConfig(
            name="PPOConfig",
            num_rollouts=256,
            chunk_size=12,
            ppo_epochs=4,
            init_kl_coef=0.05,
            target=6,
            horizon=10000,
            gamma=0.99,
            lam=0.95,
            cliprange=0.2,
            cliprange_value=0.2,
            vf_coef=1.0,
            scale_reward=None,
            ref_mean=None,
            ref_std=None,
            cliprange_reward=10,
            gen_kwargs={"max_new_tokens": 100},
            gen_experience_kwargs={
                "max_new_tokens": 100, "do_sample": False, "num_beams": 1,
                "temperature": 1.0,
            },
        ),
    )


def chrf(hyp: str, ref: str, n: int = 6, beta: float = 2.0) -> float:
    """Character n-gram F-score (local stand-in for the COMET reward)."""
    if not hyp or not ref:
        return 0.0
    precisions, recalls = [], []
    for k in range(1, n + 1):
        h = Counter(hyp[i : i + k] for i in range(len(hyp) - k + 1))
        r = Counter(ref[i : i + k] for i in range(len(ref) - k + 1))
        overlap = sum((h & r).values())
        if sum(h.values()):
            precisions.append(overlap / sum(h.values()))
        if sum(r.values()):
            recalls.append(overlap / sum(r.values()))
    if not precisions or not recalls:
        return 0.0
    p, rc = sum(precisions) / len(precisions), sum(recalls) / len(recalls)
    if p + rc == 0:
        return 0.0
    return (1 + beta**2) * p * rc / (beta**2 * p + rc)


def make_reward_fn(translation_map):
    try:
        import evaluate

        comet_metric = evaluate.load("comet", "wmt20-comet-da", progress_bar=False)

        def reward_fn(samples, prompts, outputs, **kwargs) -> List[float]:
            originals = [translation_map[p.strip()]["src"] for p in prompts]
            refs = [translation_map[p.strip()]["ref"] for p in prompts]
            scores = comet_metric.compute(
                predictions=outputs, references=refs, sources=originals
            )["scores"]
            return [float(s) for s in scores]

    except Exception:

        def reward_fn(samples, prompts, outputs, **kwargs) -> List[float]:
            refs = [translation_map[p.strip()]["ref"] for p in prompts]
            return [chrf(o.strip(), r) for o, r in zip(outputs, refs)]

    return reward_fn


def main(hparams={}):
    config = TRLConfig.update(default_config().to_dict(), hparams)

    from datasets import load_dataset

    ds = load_dataset("wmt16", "de-en", split="train[:20000]")
    prefix = "translate English to German: "
    prompts, translation_map = [], {}
    for row in ds["translation"]:
        prompt = prefix + row["en"]
        prompts.append(prompt)
        translation_map[prompt.strip()] = {"src": row["en"], "ref": row["de"]}

    reward_fn = make_reward_fn(translation_map)
    return trlx_tpu.train(
        reward_fn=reward_fn,
        prompts=prompts[:-256],
        eval_prompts=prompts[-256:],
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
