"""PPO with DENSE (per-token) rewards on IMDB sentiment (parity:
/root/reference/examples/ppo_dense_sentiments.py): the reward_fn returns a
list of per-token reward deltas per sample instead of one scalar —
exercising the dense path of the rollout engine."""

from typing import List

import trlx_tpu
from trlx_tpu.data.default_configs import TRLConfig, default_ppo_config


def get_positive_score(scores) -> float:
    return dict(map(lambda x: tuple(x.values()), scores))["POSITIVE"]


def main(hparams={}):
    config = TRLConfig.update(default_ppo_config().to_dict(), hparams)

    from datasets import load_dataset
    from transformers import pipeline as hf_pipeline

    sentiment_fn = hf_pipeline(
        "sentiment-analysis", "lvwerra/distilbert-imdb", top_k=2,
        truncation=True, batch_size=256,
    )

    def dense_reward_fn(samples: List[str], prompts: List[str], outputs: List[str],
                        tokenizer=None, **kwargs) -> List[List[float]]:
        # score the sample prefix ending at each output token; reward at
        # token t is the delta of the sentiment score between prefixes
        rewards = []
        for prompt, output in zip(prompts, outputs):
            tokens = tokenizer(output, add_special_tokens=False)["input_ids"]
            prefixes = [
                prompt + tokenizer.decode(tokens[: i + 1]) for i in range(len(tokens))
            ]
            scores = [get_positive_score(s) for s in sentiment_fn(prefixes)]
            deltas = [scores[0]] + [b - a for a, b in zip(scores, scores[1:])]
            rewards.append(deltas)
        return rewards

    imdb = load_dataset("imdb", split="train+test")
    prompts = [" ".join(review.split()[:4]) for review in imdb["text"]]

    return trlx_tpu.train(
        reward_fn=dense_reward_fn,
        prompts=prompts,
        eval_prompts=["I don't know much about Hungarian underground"] * 64,
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
