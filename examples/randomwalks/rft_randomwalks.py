"""RFT on the randomwalks task (parity:
/root/reference/examples/randomwalks/rft_randomwalks.py)."""

import trlx_tpu
from trlx_tpu.data.configs import (
    ModelConfig,
    OptimizerConfig,
    SchedulerConfig,
    TokenizerConfig,
    TrainConfig,
    TRLConfig,
)
from trlx_tpu.data.method_configs import RFTConfig

from examples.randomwalks import generate_random_walks

default_config = TRLConfig(
    train=TrainConfig(
        seq_length=11,
        epochs=100,
        total_steps=200,
        batch_size=96,
        checkpoint_interval=100000,
        eval_interval=16,
        pipeline="PromptPipeline",
        trainer="TPURFTTrainer",
        tracker=None,
        checkpoint_dir="ckpts/rft_randomwalks",
    ),
    model=ModelConfig(
        model_path="random",
        num_layers_unfrozen=-1,
        model_extra_configs={
            "transformer": dict(hidden_size=144, n_layer=4, n_head=6, n_positions=32)
        },
    ),
    tokenizer=TokenizerConfig(tokenizer_path="byte", truncation_side="right"),
    optimizer=OptimizerConfig(
        name="adamw", kwargs=dict(lr=3.0e-4, betas=(0.9, 0.95), eps=1.0e-8, weight_decay=1.0e-6)
    ),
    scheduler=SchedulerConfig(name="cosine_annealing", kwargs=dict(T_max=1000, eta_min=3.0e-4)),
    method=RFTConfig(
        name="rftconfig",
        n_generations_per_prompt=8,
        start_percentile=0.9,
        end_percentile=0.95,
        n_improve_steps=4,
        gen_kwargs=dict(max_new_tokens=9, top_k=0, top_p=1.0, do_sample=True),
    ),
)


def main(hparams={}):
    config = TRLConfig.update(default_config.to_dict(), hparams)
    metric_fn, prompts, walks, _ = generate_random_walks(seed=config.train.seed)

    if config.model.model_path == "random":
        # the reference starts from the pretrained CarperAI/randomwalks
        # checkpoint; zero-egress reproduces it with the same local BC
        # warmup the PPO example uses — RFT from a cold random model
        # never samples a single valid walk, so selection has nothing to
        # climb on (measured: optimality flat at 0 for 200 steps)
        from examples.randomwalks.ppo_randomwalks import bc_warmup

        config.model.model_path = bc_warmup(config, walks)

    return trlx_tpu.train(
        reward_fn=lambda samples, **kwargs: metric_fn(samples)["optimality"],
        prompts=prompts,
        eval_prompts=prompts,
        metric_fn=lambda samples, **kwargs: metric_fn(samples),
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
