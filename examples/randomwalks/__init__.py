from examples.randomwalks.randomwalks import generate_random_walks  # noqa: F401
