"""Synthetic shortest-path task on a random directed graph.

Parity: /root/reference/examples/randomwalks/randomwalks.py (220 LoC) —
same task: nodes are letters, the model is trained to continue a walk
from a start node to the goal node 'a' in as few steps as possible;
`metric_fn` scores optimality in [0, 1] against the true shortest path
(computed here with a plain BFS instead of networkx, which this image
doesn't ship). Works with the byte tokenizer: one letter = one token, no
delimiter needed.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


def _bfs_shortest_lengths(adj: np.ndarray, goal: int, max_length: int) -> List[int]:
    """Shortest path length (in nodes, incl. endpoints, capped) from every
    non-goal node to `goal` over directed edges."""
    n = adj.shape[0]
    out = []
    for start in range(n):
        if start == goal:
            continue
        frontier = {start}
        seen = {start}
        dist = None
        for depth in range(1, max_length + 1):
            if goal in frontier:
                dist = depth
                break
            nxt = set()
            for u in frontier:
                nxt.update(np.nonzero(adj[u])[0].tolist())
            frontier = nxt - seen
            seen |= frontier
            if not frontier:
                break
        out.append(dist if dist is not None else max_length)
    return out


def generate_random_walks(
    n_nodes: int = 21,
    max_length: int = 10,
    n_walks: int = 1000,
    p_edge: float = 0.1,
    seed: int = 1002,
) -> Tuple[Callable, List[str], List[str], np.ndarray]:
    """Returns (metric_fn, eval_prompts, sample_walks, adjacency_matrix)."""
    rng = np.random.RandomState(seed)

    while True:
        adj = rng.rand(n_nodes, n_nodes) > (1 - p_edge)
        np.fill_diagonal(adj, 0)
        if np.all(adj.sum(1)):  # every node has at least one outgoing edge
            break

    goal = 0
    adj[goal, :] = 0
    adj[goal, goal] = 1

    node_to_char = {ix: chr(ix + ord("a")) for ix in range(n_nodes)}
    char_to_node = {c: n for n, c in node_to_char.items()}

    sample_walks: List[str] = []
    for _ in range(n_walks):
        node = rng.randint(1, n_nodes)  # any non-goal start
        walk = [node]
        for _step in range(max_length - 1):
            node = rng.choice(np.nonzero(adj[node])[0])
            walk.append(node)
            if node == goal:
                break
        sample_walks.append("".join(node_to_char[ix] for ix in walk))

    shortest_lengths = _bfs_shortest_lengths(adj, goal, max_length)

    def metric_fn(samples: List[str], **kwargs) -> Dict[str, List[float]]:
        invalid_path_length = 100
        lengths: List[float] = []
        optimal: List[int] = []
        for sample_str in samples:
            nodes = [char_to_node.get(c, 1000) for c in sample_str.strip()]
            length: Optional[float] = None
            for i, node in enumerate(nodes):
                if node >= n_nodes or (i > 0 and not adj[nodes[i - 1], node]):
                    length = invalid_path_length
                    break
                if node == goal:
                    length = i + 1
                    break
            if length is None:
                length = invalid_path_length
            lengths.append(float(length))
            start = nodes[0] if nodes and nodes[0] < n_nodes else 1
            optimal.append(shortest_lengths[start - 1])

        lengths_arr = np.asarray(lengths, np.float32)
        bound = np.where(lengths_arr == invalid_path_length, max_length, lengths_arr)
        optimality = (max_length - bound) / (
            max_length - np.asarray(optimal, np.float32)
        )
        return {"lengths": lengths, "optimality": optimality.tolist()}

    eval_prompts = sorted(set(w[0] for w in sample_walks))
    return metric_fn, eval_prompts, sample_walks, adj
