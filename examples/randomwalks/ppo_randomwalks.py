"""PPO on the randomwalks task (parity:
/root/reference/examples/randomwalks/ppo_randomwalks.py). Runs with zero
network egress: a small random-init decoder trained from scratch with the
built-in byte tokenizer."""

import trlx_tpu
from trlx_tpu.data.configs import (
    ModelConfig,
    OptimizerConfig,
    SchedulerConfig,
    TokenizerConfig,
    TrainConfig,
    TRLConfig,
)
from trlx_tpu.data.method_configs import PPOConfig

from examples.randomwalks import generate_random_walks

default_config = TRLConfig(
    train=TrainConfig(
        seq_length=10,
        epochs=20,
        total_steps=1000,
        batch_size=96,
        checkpoint_interval=10000,
        eval_interval=20,
        pipeline="PromptPipeline",
        trainer="TPUPPOTrainer",
        tracker=None,
        checkpoint_dir="ckpts/ppo_randomwalks",
    ),
    model=ModelConfig(
        model_path="random",
        num_layers_unfrozen=-1,
        model_extra_configs={
            "transformer": dict(hidden_size=144, n_layer=4, n_head=6, n_positions=32)
        },
    ),
    tokenizer=TokenizerConfig(tokenizer_path="byte", truncation_side="right"),
    optimizer=OptimizerConfig(
        name="adamw", kwargs=dict(lr=3.0e-4, betas=(0.9, 0.95), eps=1.0e-8, weight_decay=1.0e-6)
    ),
    scheduler=SchedulerConfig(name="cosine_annealing", kwargs=dict(T_max=10000, eta_min=3.0e-4)),
    method=PPOConfig(
        name="PPOConfig",
        num_rollouts=96,
        chunk_size=96,
        ppo_epochs=4,
        init_kl_coef=0,
        target=None,
        horizon=10000,
        gamma=1,
        lam=0.95,
        cliprange=0.2,
        cliprange_value=0.2,
        vf_coef=1.2,
        scale_reward="ignored",
        ref_mean=None,
        ref_std=None,
        cliprange_reward=1,
        gen_kwargs=dict(max_new_tokens=9, top_k=0, top_p=1.0, do_sample=True),
    ),
)


def bc_warmup(config, walks) -> str:
    """Behavior-clone the random-walk corpus so PPO starts from a model
    that emits valid walks. (The reference starts from the pretrained
    CarperAI/randomwalks checkpoint — examples/randomwalks/ppo_randomwalks.py:31
    — which the zero-egress TPU environment must reproduce locally.)"""
    import os

    sft_dir = os.path.join(config.train.checkpoint_dir, "bc_warmup")
    model_dir = os.path.join(sft_dir, "hf_model")
    if not os.path.exists(os.path.join(model_dir, "trlx_tpu_config.json")):
        from trlx_tpu.data.method_configs import SFTConfig

        sft_config = TRLConfig.from_dict(
            dict(
                config.to_dict(),
                method=SFTConfig(name="sftconfig", gen_kwargs=dict(max_new_tokens=9)).to_dict(),
            )
        ).evolve(
            train=dict(
                trainer="TPUSFTTrainer", total_steps=200, epochs=40,
                eval_interval=1000, checkpoint_interval=1000,
                checkpoint_dir=sft_dir,
            ),
        )
        trainer = trlx_tpu.train(
            samples=[(w[0], w[1:]) for w in walks], config=sft_config
        )
        trainer.save_pretrained(model_dir)
    return model_dir


def main(hparams={}):
    config = TRLConfig.update(default_config.to_dict(), hparams)
    metric_fn, prompts, walks, _ = generate_random_walks(seed=config.train.seed)

    config.model.model_path = bc_warmup(config, walks)

    return trlx_tpu.train(
        reward_fn=lambda samples, **kwargs: metric_fn(samples)["optimality"],
        prompts=prompts,
        eval_prompts=prompts,
        metric_fn=lambda samples, **kwargs: metric_fn(samples),
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
