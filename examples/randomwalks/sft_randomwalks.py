"""SFT on the randomwalks shortest-path task: supervised on OPTIMAL
walks (BFS gold paths), evaluated by the same optimality metric the
PPO/ILQL examples use.

The reference's benchmark matrix records a learning curve per
example/algorithm (ref scripts/benchmark.sh:44-70); randomwalks is its
zero-egress task, so this is the SFT row of that matrix. Training on
gold shortest paths (rather than the random-walk corpus the PPO BC
warmup uses) gives SFT a real learning signal: eval optimality climbs
toward the supervised ceiling instead of the corpus average.
"""

from __future__ import annotations

from typing import List

import numpy as np

import trlx_tpu
from trlx_tpu.data.configs import (
    ModelConfig,
    OptimizerConfig,
    SchedulerConfig,
    TokenizerConfig,
    TrainConfig,
    TRLConfig,
)
from trlx_tpu.data.method_configs import SFTConfig

from examples.randomwalks import generate_random_walks

default_config = TRLConfig(
    train=TrainConfig(
        seq_length=11,
        epochs=100,
        total_steps=200,
        batch_size=96,
        checkpoint_interval=100000,
        eval_interval=16,
        pipeline="PromptPipeline",
        trainer="TPUSFTTrainer",
        tracker=None,
        checkpoint_dir="ckpts/sft_randomwalks",
    ),
    model=ModelConfig(
        model_path="random",
        num_layers_unfrozen=-1,
        model_extra_configs={
            "transformer": dict(hidden_size=144, n_layer=4, n_head=6, n_positions=32)
        },
    ),
    tokenizer=TokenizerConfig(tokenizer_path="byte", truncation_side="right"),
    optimizer=OptimizerConfig(
        name="adamw", kwargs=dict(lr=3.0e-4, betas=(0.9, 0.95), eps=1.0e-8, weight_decay=1.0e-6)
    ),
    scheduler=SchedulerConfig(name="cosine_annealing", kwargs=dict(T_max=1000, eta_min=3.0e-4)),
    method=SFTConfig(
        name="sftconfig",
        gen_kwargs=dict(max_new_tokens=9, do_sample=False),
    ),
)


def optimal_walks(adj: np.ndarray, max_length: int = 10) -> List[str]:
    """One BFS-shortest path from every non-goal start node to the goal
    (node 0), as letter strings — the SFT gold corpus."""
    n = adj.shape[0]
    goal = 0
    walks = []
    for start in range(1, n):
        # BFS with parent pointers
        parent = {start: None}
        frontier = [start]
        found = False
        while frontier and not found:
            nxt = []
            for u in frontier:
                for v in np.nonzero(adj[u])[0].tolist():
                    if v not in parent:
                        parent[v] = u
                        if v == goal:
                            found = True
                        nxt.append(v)
            frontier = nxt
        if not found:
            continue
        path = [goal]
        while parent[path[-1]] is not None:
            path.append(parent[path[-1]])
        path = path[::-1][:max_length]
        if path[-1] != goal:
            continue
        walks.append("".join(chr(ix + ord("a")) for ix in path))
    return walks


def main(hparams={}):
    config = TRLConfig.update(default_config.to_dict(), hparams)
    metric_fn, eval_prompts, _walks, adj = generate_random_walks(
        seed=config.train.seed
    )
    gold = optimal_walks(adj)

    return trlx_tpu.train(
        samples=[(w[0], w[1:]) for w in gold] * 8,
        eval_prompts=eval_prompts,
        metric_fn=lambda samples, **kwargs: metric_fn(samples),
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
