"""ILQL on the randomwalks task (parity:
/root/reference/examples/randomwalks/ilql_randomwalks.py): offline
training on the random walk corpus with per-walk optimality rewards."""

import trlx_tpu
from trlx_tpu.data.configs import (
    ModelConfig,
    OptimizerConfig,
    SchedulerConfig,
    TokenizerConfig,
    TrainConfig,
    TRLConfig,
)
from trlx_tpu.data.method_configs import ILQLConfig

from examples.randomwalks import generate_random_walks

default_config = TRLConfig(
    train=TrainConfig(
        seq_length=11,
        epochs=100,
        total_steps=1000,
        batch_size=96,
        checkpoint_interval=100000,
        eval_interval=16,
        pipeline="PromptPipeline",
        trainer="TPUILQLTrainer",
        tracker=None,
        checkpoint_dir="ckpts/ilql_randomwalks",
    ),
    model=ModelConfig(
        model_path="random",
        num_layers_unfrozen=-1,
        model_extra_configs={
            "transformer": dict(hidden_size=144, n_layer=4, n_head=6, n_positions=32)
        },
    ),
    tokenizer=TokenizerConfig(tokenizer_path="byte", truncation_side="right"),
    optimizer=OptimizerConfig(
        name="adamw", kwargs=dict(lr=2.0e-4, betas=(0.9, 0.95), eps=1.0e-8, weight_decay=1.0e-6)
    ),
    scheduler=SchedulerConfig(name="cosine_annealing", kwargs=dict(T_max=1000, eta_min=2.0e-4)),
    method=ILQLConfig(
        name="ilqlconfig",
        tau=0.9,
        gamma=0.99,
        cql_scale=0.1,
        awac_scale=1,
        alpha=0.1,
        beta=0,
        steps_for_target_q_sync=5,
        two_qs=True,
        gen_kwargs=dict(max_new_tokens=9, top_k=10, beta=[0, 1, 100], temperature=1.0),
    ),
)


def main(hparams={}):
    config = TRLConfig.update(default_config.to_dict(), hparams)
    metric_fn, eval_prompts, walks, _ = generate_random_walks(seed=config.train.seed)
    rewards = metric_fn(walks)["optimality"]

    return trlx_tpu.train(
        samples=walks,
        rewards=rewards,
        eval_prompts=eval_prompts,
        metric_fn=lambda samples, **kwargs: metric_fn(samples),
        config=config,
    )


if __name__ == "__main__":
    import json
    import sys

    hparams = {} if len(sys.argv) == 1 else json.loads(sys.argv[1])
    main(hparams)
