#!/bin/bash
# Sweep entry (parity: /root/reference/scripts/sweep-cw.sh — the
# reference dispatched ray workers; trials here run sequentially on the
# full mesh).
#
# Usage: scripts/sweep.sh configs/sweeps/ppo_sweep.yml examples/ppo_sentiments.py [output-dir]
set -euo pipefail
cd "$(dirname "$0")/.."
CONFIG="${1:?usage: sweep.sh <sweep.yml> <script.py> [output-dir]}"
SCRIPT="${2:?usage: sweep.sh <sweep.yml> <script.py> [output-dir]}"
python -m trlx_tpu.sweep "$SCRIPT" --config "$CONFIG" --output "${3:-sweeps_out}"
