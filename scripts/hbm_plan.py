#!/usr/bin/env python
"""Offline HBM preflight: the memory doctor's admission-control plan
from a config file ALONE — no trainer, no device, no allocation.

Builds the analytic per-phase HBM plan (utils/memdoctor.analytic_plan:
params/optimizer/reference from an analytic parameter count;
activations/grads/logits for the train phase; decode-engine page pools
or the static KV cache for the rollout phase; transport/fleet host
buffers as FYI rows) and prints the itemized report the in-trainer
preflight would print — so a 20B sizing question is answered on a
login node in milliseconds instead of by a dead run on the pod.

Usage:
    python scripts/hbm_plan.py configs/ppo_config.yml
    python scripts/hbm_plan.py config.yml --hbm-gb 16        # per-device budget
    python scripts/hbm_plan.py config.yml --json             # machine-readable
    python scripts/hbm_plan.py config.yml --set train.batch_size=512 ...

Exit code 0 = plan fits (or no budget known: report only);
1 = over budget — the same verdict `train.memory.preflight: enforce`
would reach in learn(), reached before any compile.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# must run on build/login nodes with no accelerator attached
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("config", help="TRLConfig YAML path")
    parser.add_argument(
        "--hbm-gb", type=float, default=0.0,
        help="per-device HBM budget in GiB (overrides train.memory."
             "hbm_bytes; 0 = use the config / report-only)",
    )
    parser.add_argument(
        "--devices", type=int, default=0,
        help="device count that resolves auto mesh axes (dp/fsdp = -1 "
             "means 'absorb remaining devices', unknowable offline); "
             "0 assumes 1 on the auto axis (worst case, noted in the "
             "plan)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="emit the plan as one JSON object instead of the table",
    )
    parser.add_argument(
        "--set", action="append", default=[], metavar="PATH=VALUE",
        help="dotted-path config overrides, e.g. train.batch_size=512 "
             "(applied before planning; repeatable)",
    )
    args = parser.parse_args(argv)

    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.utils.memdoctor import analytic_plan

    config = TRLConfig.load_yaml(args.config)
    if args.set:
        overrides = {}
        for item in args.set:
            path, _, raw = item.partition("=")
            if not _:
                parser.error(f"--set {item!r}: expected PATH=VALUE")
            try:
                overrides[path] = json.loads(raw)
            except json.JSONDecodeError:
                overrides[path] = raw
        config = TRLConfig.update(config, overrides)

    plan = analytic_plan(
        config, hbm_bytes=int(args.hbm_gb * (1 << 30)), devices=args.devices
    )
    if args.json:
        print(json.dumps(plan.to_dict(), indent=2))
    else:
        print(plan.report())
    if plan.over_budget():
        if not args.json:
            print(
                "\nVERDICT: OVER BUDGET — train.memory.preflight: enforce "
                "would reject this config before any compile. Lower "
                "batch/seq/chunk sizes, raise mesh fsdp, set "
                "train.logit_chunks / grads_dtype / remat_policy, or "
                "shrink method.gen_engine pool knobs."
            )
        return 1
    if not args.json:
        print("\nVERDICT: fits" if plan.budget_bytes > 0 else
              "\nVERDICT: no budget known (pass --hbm-gb) — report only")
    return 0


if __name__ == "__main__":
    sys.exit(main())
