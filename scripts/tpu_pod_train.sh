#!/bin/bash
# Launch a training script on every worker of a TPU pod slice
# (parity: /root/reference/scripts/slurm_train.sh — the reference's
# multi-node SLURM launcher; on TPU VMs the launcher is gcloud).
#
# Usage: TPU=<name> ZONE=<zone> scripts/tpu_pod_train.sh examples/ppo_sentiments.py '{"train.mesh": {"fsdp": 8}}'
set -euo pipefail

TPU="${TPU:?set TPU=<tpu-vm name>}"
ZONE="${ZONE:?set ZONE=<gce zone>}"
SCRIPT="${1:?usage: tpu_pod_train.sh <script.py> [hparams-json]}"
HPARAMS="${2:-{}}"

# every worker runs the identical SPMD program; jax.distributed
# auto-detects the pod topology from the TPU runtime env
gcloud compute tpus tpu-vm ssh "$TPU" --zone "$ZONE" --worker=all \
  --command "cd ~/trlx_tpu && python $SCRIPT '$HPARAMS'"
