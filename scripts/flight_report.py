#!/usr/bin/env python
"""Render a flight-recorder stream into a human timeline.

Input: a checkpoint dir (reads ``<dir>/flight/``), a flight dir, or a
single ``flight-*.jsonl`` file's directory. Output: per-run summary —
a per-cycle table (wall, samples/s, phase breakdown), the event
overlay (guardrail trips/actions, chaos injections, OOM-ladder rungs,
watermark crossings, checkpoints/restores, supervisor records) keyed
into the cycles they happened in, and slowest-phase attribution.

Pure stdlib + the jax-free ``trlx_tpu.obs.recorder`` reader, so it
runs on any login node against a live run's directory.

Usage:
    python scripts/flight_report.py ckpts
    python scripts/flight_report.py ckpts/flight --last 20
    python scripts/flight_report.py ckpts --run <run_id>
Exit code 0 = rendered; 1 = no flight stream found.
"""

from __future__ import annotations

import argparse
import datetime
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from trlx_tpu.obs.recorder import flight_files, iter_rows  # noqa: E402

# event kinds rendered in the overlay (cycle rows are the table)
_EVENT_ORDER = (
    "run_start", "restore", "guardrail_trip", "guardrail_action", "chaos",
    "oom", "memory_watermark", "hosts", "checkpoint", "supervisor",
    "run_end",
)


def _resolve_dir(path: str) -> str:
    for candidate in (path, os.path.join(path, "flight")):
        if flight_files(candidate):
            return candidate
    return path


def _fmt_t(t) -> str:
    try:
        return datetime.datetime.fromtimestamp(float(t)).strftime("%H:%M:%S")
    except Exception:
        return "?"


def _event_line(row: dict) -> str:
    kind = row.get("kind", "?")
    skip = {"t", "run", "kind", "cycle", "step", "pv"}
    detail = " ".join(
        f"{k}={row[k]}" for k in row if k not in skip
    )
    return f"    {_fmt_t(row.get('t'))}  [{kind}] {detail}".rstrip()


def render(directory: str, last: int = 0, run: str = "") -> str:
    rows = list(iter_rows(directory))
    if not rows:
        return ""
    runs = list(dict.fromkeys(r.get("run", "?") for r in rows))
    if run:
        runs = [r for r in runs if r.startswith(run)]
    lines = [f"flight stream: {directory} ({len(rows)} rows, "
             f"{len(runs)} run(s))"]
    # external rows (supervisor) carry their own run id: fold them into
    # every rendered run's overlay by time — they describe the stream,
    # not one incarnation
    external = [r for r in rows if r.get("kind") == "supervisor"]
    for run_id in runs:
        rrows = [r for r in rows if r.get("run") == run_id]
        if all(r.get("kind") == "supervisor" for r in rrows):
            continue
        merged = rrows + external
        merged.sort(key=lambda r: r.get("t", 0))
        # group by STREAM ORDER, not cycle number: a cycle row is
        # written when its cycle CLOSES, so the events preceding it
        # happened inside it — and cycle numbers can repeat within one
        # run after a resume/rollback rewinds the counter, so they
        # cannot key the overlay
        groups = []    # (cycle_row, events that happened inside it)
        pending = []
        for r in merged:
            if r.get("kind") == "cycle":
                groups.append((r, pending))
                pending = []
            else:
                pending.append(r)
        cycles = [c for c, _ in groups]
        n_events = len(merged) - len(cycles)
        lines.append(f"\nrun {run_id}: {len(cycles)} cycles, "
                     f"{n_events} events")
        shown = groups[-last:] if last else groups
        # table columns: the union of phases, widest totals first
        totals: dict = {}
        for c in cycles:
            for k, v in (c.get("phases") or {}).items():
                totals[k] = totals.get(k, 0.0) + float(v)
        phase_cols = [k for k, _ in sorted(totals.items(),
                                           key=lambda kv: -kv[1])][:6]
        header = (
            f"  {'cycle':>5} {'step':>6} {'wall_s':>8} {'smp':>5} "
            f"{'smp/s':>7} " + " ".join(f"{p[:10]:>10}" for p in phase_cols)
            + "  slowest"
        )
        lines.append(header)
        for c, events in shown:
            for e in events:
                lines.append(_event_line(e))
            phases = c.get("phases") or {}
            slowest = max(phases.items(), key=lambda kv: kv[1])[0] if phases else "-"
            cells = " ".join(
                f"{phases.get(p, 0.0):>10.3f}" for p in phase_cols
            )
            lines.append(
                f"  {c.get('cycle', '?'):>5} {str(c.get('step', '-')):>6} "
                f"{c.get('wall_s', 0.0):>8.3f} {str(c.get('samples', '-')):>5} "
                f"{str(c.get('samples_per_sec', '-')):>7} {cells}  {slowest}"
            )
        if pending:  # events after the last cycle row (run_end, ...)
            lines.append("  events after the last cycle:")
            for e in pending:
                lines.append(_event_line(e))
        # attribution summary
        if totals:
            wall_total = sum(float(c.get("wall_s", 0.0)) for c in cycles)
            top = sorted(totals.items(), key=lambda kv: -kv[1])[:3]
            lines.append(
                "  slowest-phase attribution: "
                + ", ".join(
                    f"{k} {v:.3f}s"
                    + (f" ({v / wall_total:.0%})" if wall_total else "")
                    for k, v in top
                )
            )
        if cycles:
            worst = max(cycles, key=lambda c: float(c.get("wall_s", 0.0)))
            lines.append(
                f"  worst cycle: #{worst.get('cycle')} "
                f"wall {worst.get('wall_s')}s "
                f"(step {worst.get('step')}, phases {worst.get('phases')})"
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("path", help="checkpoint dir or flight dir")
    parser.add_argument("--last", type=int, default=0,
                        help="render only the last N cycles per run")
    parser.add_argument("--run", default="",
                        help="render only run ids starting with this prefix")
    args = parser.parse_args(argv)
    directory = _resolve_dir(os.path.abspath(args.path))
    out = render(directory, last=args.last, run=args.run)
    if not out:
        print(f"no flight-recorder stream under {args.path} "
              "(expected flight-*.jsonl; is train.obs enabled?)")
        return 1
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
