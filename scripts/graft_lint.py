#!/usr/bin/env python
"""graft-lint CLI: AST-level enforcement of donation safety, trace
purity, RNG-stream discipline and config<->docs sync (ISSUE 13;
runbook: docs/static_analysis.md).

Usage:
    python scripts/graft_lint.py                      # full repo, exit 1 on findings
    python scripts/graft_lint.py path/to/file.py ...  # just these files
    python scripts/graft_lint.py --rules donation,sync-zone
    python scripts/graft_lint.py --baseline lint_baseline.json
    python scripts/graft_lint.py --diff lint_baseline.json
    python scripts/graft_lint.py --update-manifests   # append-only regen
    python scripts/graft_lint.py --json               # findings as JSON lines

Suppressions are inline pragmas on the flagged line, reason required:
    x = step(x, b)  # graft-lint: allow[donation] rematerialized below

No jax, no trlx_tpu training imports — safe on a login node, and the
analysis package is never imported by the training path (bench.py
--smoke pins that).
"""

import argparse
import json
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# load trlx_tpu.analysis WITHOUT executing trlx_tpu/__init__.py (which
# imports the jax training stack): a bare namespace shim keeps this CLI
# importable on a login node with nothing but the stdlib + pyyaml
if "trlx_tpu" not in sys.modules:
    _pkg = types.ModuleType("trlx_tpu")
    _pkg.__path__ = [os.path.join(REPO, "trlx_tpu")]
    sys.modules["trlx_tpu"] = _pkg

from trlx_tpu.analysis import RULES, runner  # noqa: E402
from trlx_tpu.analysis import manifests  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", help=(
        "files to lint (repo-relative or absolute); default: the whole "
        "repo incl. the repo-level manifest and config<->docs checks"
    ))
    ap.add_argument("--repo", default=REPO, help=(
        "tree root to lint (default: this checkout) — lets tests and "
        "fixtures run the full pipeline against a scratch tree"
    ))
    ap.add_argument("--rules", default=None, help=(
        f"comma-separated rule filter (known: {', '.join(RULES)})"
    ))
    ap.add_argument("--baseline", metavar="OUT.json", default=None, help=(
        "write the (unsuppressed) findings to OUT.json and exit 0 — "
        "the snapshot future --diff runs compare against"
    ))
    ap.add_argument("--diff", metavar="BASELINE.json", default=None, help=(
        "report only findings NOT in BASELINE.json (stable keys: "
        "rule+file+flagged text, immune to line drift)"
    ))
    ap.add_argument("--update-manifests", action="store_true", help=(
        "regenerate tests/golden/ chaos-site + guardrail-signal "
        "manifests, append-only (refuses inserts/reorders/deletes)"
    ))
    ap.add_argument("--json", action="store_true",
                    help="one JSON object per finding instead of text")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also list pragma-suppressed findings")
    args = ap.parse_args(argv)

    if args.update_manifests:
        try:
            for note in manifests.update(args.repo):
                print(f"WROTE {note}")
        except ValueError as e:
            print(f"FAIL  {e}")
            return 1
        return 0

    rules = args.rules.split(",") if args.rules else None
    if rules:
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            ap.error(f"unknown rule(s) {unknown}; known: {', '.join(RULES)}")

    paths = None
    if args.paths:
        paths = []
        for p in args.paths:
            # non-absolute paths are repo-relative (the --repo tree),
            # not CWD-relative; absolute paths are mapped into the repo
            ap_abs = p if os.path.isabs(p) else os.path.join(args.repo, p)
            paths.append(
                os.path.relpath(ap_abs, args.repo).replace(os.sep, "/")
            )

    findings = runner.run_repo(args.repo, paths=paths, rules=rules)
    live = runner.active(findings)
    suppressed = [f for f in findings if f.suppressed_by is not None]

    if args.baseline:
        runner.write_baseline(args.baseline, findings)
        print(f"WROTE {args.baseline}: {len(live)} finding(s) recorded")
        return 0

    if args.diff:
        try:
            live = runner.diff_against(args.diff, findings)
        except (OSError, ValueError, KeyError) as e:
            print(f"FAIL  cannot diff against {args.diff}: {e}")
            return 1

    for f in sorted(live, key=lambda f: (f.file, f.line, f.rule)):
        print(json.dumps(f.to_dict()) if args.json else f"FAIL  {f.render()}")
    if args.show_suppressed:
        for f in sorted(suppressed, key=lambda f: (f.file, f.line)):
            print(f"allow {f.render()}  [pragma: {f.suppressed_by}]")

    if live:
        mode = "new findings vs baseline" if args.diff else "finding(s)"
        print(f"\ngraft-lint: {len(live)} {mode} "
              f"({len(suppressed)} suppressed by pragma). "
              "Runbook: docs/static_analysis.md")
        return 1
    print(f"OK    graft-lint clean ({len(suppressed)} pragma-suppressed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
