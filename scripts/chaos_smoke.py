#!/usr/bin/env python
"""Robustness smoke: a short PPO `learn()` under injected chaos (NaN
burst in the fused-block losses, a reward-service timeout, a bit-flipped
committed checkpoint shard, and a cross-host fingerprint divergence),
with the guardrails watchdog — including the consistency watchdog — the
resilient reward path, checkpoint integrity manifests and the
overlapped rollout prefetch all armed.

Prints one JSON line and exits non-zero if the run does not recover
without human intervention (full step budget completed, >= 1
auto-rollback to the last good checkpoint, the corrupted checkpoint
quarantined — not loaded, not deleted — the divergence tripping the
ladder, finite final reward).

It also proves the HANG DOCTOR end to end: `stall_rollout`,
`stall_collective` and `stall_rollout_engine` (the same rollout wedge
with the decode engine + experience transport armed) schedules run in
child processes whose injected sleep is ~13x the `train.watchdog`
deadline, and each child must detect the stall within the deadline,
log the all-thread stack dump, write an emergency snapshot (restorable
via `trainer.load()`, asserted here) and exit with the "stalled" exit
class (`watchdog.EXIT_STALLED = 87`) — distinguishable from a crash.

And it proves the EXPERIENCE TRANSPORT (`ppo.exp.enabled`,
trlx_tpu/exp/): a producer killed mid-lease (lease expiry ->
re-dispatch), a duplicate delivery (consumer dedup) and a queue wedge
(bounded back-pressure wait) must leave the loss/reward stream
BIT-IDENTICAL to the fault-free exp run, and a `stale_flood` schedule
must trip the `staleness` guardrail signal without aborting.

And it proves the MEMORY DOCTOR (`train.memory`, utils/memdoctor.py):
injected `oom_prefill` / `oom_fused_block` RESOURCE_EXHAUSTED faults
must recover through the degradation ladder (gen-engine pool shrink;
microbatch split with grad-accum compensation) with the full step
budget completed, a finite final loss, and the degradation persisted
in state.json; `hbm_creep` must trip the `memory` guardrail signal
without an abort; and a deliberately over-budget config must be
REJECTED by preflight with an itemized per-phase HBM report before
any rollout or compile is paid.

And it proves the SERVING TIER (`train.serve`, trlx_tpu/serve/): a
background serve load must leave the training loss stream BIT-IDENTICAL
to the no-serving run; `serve_lane_starvation` ages requests into
deadline eviction (with an idle pinned session's pages RECLAIMED),
`serve_request_timeout` evicts an already-expired request with a
`timeout` result, and `serve_transport_drop` message loss converges to
exactly-once delivery via re-post + dedup.

CPU-friendly (tiny random model, byte tokenizer, zero egress) — run it
after touching guardrails / checkpointing / the rollout loop:
`python scripts/chaos_smoke.py` (equivalently `python bench.py --chaos`).
See docs/robustness.md for the fault-schedule format.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

if __name__ == "__main__":
    print(json.dumps({"metric": "ppo_chaos_smoke", **bench.bench_chaos()}))
