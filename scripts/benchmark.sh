#!/bin/bash
# Benchmark matrix (parity: /root/reference/scripts/benchmark.sh — clone
# a branch, run the example matrix, record metrics). Air-gapped subset:
# the randomwalks examples train from scratch; bench.py measures PPO
# throughput on a GPT2-small-class workload.
set -e
cd "$(dirname "$0")/.."

echo "== randomwalks smoke matrix =="
for script in ppo ilql rft; do
  echo "-- ${script}_randomwalks"
  python - <<PY
import sys; sys.path.insert(0, ".")
from examples.randomwalks.${script}_randomwalks import main
main({"train.total_steps": 40, "train.eval_interval": 20,
      "train.checkpoint_interval": 100000,
      "train.checkpoint_dir": "/tmp/bench_rw_${script}"})
PY
done

echo "== throughput =="
python bench.py
