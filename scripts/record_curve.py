"""Convert a tracker metrics.jsonl run into a committed curve artifact.

The learning-curve protocol (parity: ref trlx/reference.py — W&B curve
diffing) keeps a recorded reward-vs-step JSONL under docs/curves/ so
regressions diff against a committed artifact instead of a prose claim.
This script trims a raw tracker log (utils/trackers.py) down to the
curve-relevant keys and prepends a meta line.

Usage:
    python scripts/record_curve.py /tmp/run/metrics.jsonl \
        docs/curves/randomwalks_ilql.jsonl \
        --task "randomwalks ILQL (examples/randomwalks/ilql_randomwalks.py)" \
        --protocol "offline ILQL, 1000 steps, eval every 100" \
        --keys reward/mean metrics/optimality losses/loss
"""

from __future__ import annotations

import argparse
import json
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("src")
    ap.add_argument("dst")
    ap.add_argument("--task", required=True)
    ap.add_argument("--protocol", required=True)
    ap.add_argument("--hardware", default="1x TPU v5e via tunnel")
    ap.add_argument(
        "--keys", nargs="+",
        default=["reward/mean", "metrics/optimality", "losses/loss"],
        help="metric keys to keep; a key K also keeps sweep variants K@...",
    )
    ap.add_argument(
        "--final-key", default="metrics/optimality",
        help="meta final_* value = last record carrying this key (or a sweep variant)",
    )
    ap.add_argument("--extra-meta", default="{}", help="JSON merged into the meta line")
    args = ap.parse_args()

    def keep(k: str) -> bool:
        return any(k == key or k.startswith(key + "@") for key in args.keys)

    rows, final = [], {}
    with open(args.src) as f:
        for line in f:
            rec = json.loads(line)
            kept = {k: round(v, 4) for k, v in rec.items() if keep(k)}
            if not kept:
                continue
            if "_step" not in rec:
                # a non-Tracker jsonl row defaulting to step 0 mid-file
                # would violate the monotonic-steps contract that
                # tests/test_curves.py enforces only AFTER the artifact
                # is committed — skip it at record time instead
                continue
            rows.append({"step": rec["_step"], **kept})
            fk = {
                k: v for k, v in kept.items()
                if k == args.final_key or k.startswith(args.final_key + "@")
            }
            if fk:
                final = fk

    meta = {
        "task": args.task,
        "protocol": args.protocol,
        "hardware": args.hardware,
        "date": time.strftime("%Y-%m-%d"),
        **{
            "final_" + k.split("/")[-1]: v
            for k, v in sorted(final.items())
        },
        "reference_protocol": "curve parity per ref trlx/reference.py",
        **json.loads(args.extra_meta),
    }
    with open(args.dst, "w") as f:
        f.write(json.dumps({"meta": meta}) + "\n")
        for row in rows:
            f.write(json.dumps(row) + "\n")
    print(f"wrote {args.dst}: {len(rows)} rows, meta={meta}")


if __name__ == "__main__":
    main()
