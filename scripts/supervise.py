#!/usr/bin/env python
"""Exit-class-aware run supervisor.

Wraps a training command and routes each exit by its CLASS instead of
blindly relaunching (docs/robustness.md "Exit classes"):

  0   clean finish / handled preemption — honored: the supervisor stops.
  87  stalled (`watchdog.EXIT_STALLED`, the hang doctor) — relaunch
      pointed at the newest emergency snapshot under ``--checkpoint-dir``
      via the ``TRLX_TPU_RESUME_FROM`` env override (api.py); emergency
      snapshots are deliberately invisible to auto-discovery, so without
      this routing a relaunch would silently lose everything after the
      last interval commit. Falls back to a plain relaunch (auto-resume)
      when no snapshot exists.
  *   crash (exception, guardrails abort, OOM-kill) — relaunch with
      exponential backoff (doubling from ``--backoff``, capped at
      ``--backoff-max``), after FLAP DETECTION: ``--flap-limit`` exits
      within ``--flap-window`` seconds of their own launch means the
      process is dying faster than it can make progress (a code bug, not
      an infra event) — the supervisor gives up instead of burning the
      allocation, with a ``gave_up`` ledger entry naming the streak.

Every decision is appended to a machine-readable JSONL RUN LEDGER
(``--ledger``, default ``<checkpoint-dir>/run_ledger.jsonl``): one
record per exit with the attempt number, exit code + class, run wall
seconds, the action taken (``done`` / ``restart`` / ``resume_snapshot``
/ ``gave_up``), the backoff applied and any resume path — what a fleet
dashboard ingests to tell "stalls on host X" from "crash-looping
everywhere".

Usage:
    python scripts/supervise.py --checkpoint-dir ckpts -- \
        python examples/ppo_dense_sentiments.py
    python scripts/supervise.py --max-restarts 20 --backoff 5 -- \
        python train.py --config my.yml

Everything after ``--`` is the child command, run as-is with the
current environment (+ ``TRLX_TPU_RESUME_FROM`` when routing a stall).
Tested end to end in child processes: tests/test_supervisor.py.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from trlx_tpu.utils.checkpointing import (  # noqa: E402
    EMERGENCY_PREFIX,
    is_committed,
)
from trlx_tpu.utils.watchdog import EXIT_STALLED  # noqa: E402

EXIT_CLASSES = {0: "clean", EXIT_STALLED: "stalled"}


def classify(code: int) -> str:
    return EXIT_CLASSES.get(code, "crash")


def _committed_steps(checkpoint_dir: str, prefix: str):
    """(step, path) pairs of committed ``<prefix><step>`` dirs."""
    out = []
    for entry in os.listdir(checkpoint_dir):
        if not entry.startswith(prefix):
            continue
        suffix = entry[len(prefix):]
        if not suffix.isdigit():
            continue
        path = os.path.join(checkpoint_dir, entry)
        if is_committed(path):
            out.append((int(suffix), path))
    return out


def latest_emergency_snapshot(checkpoint_dir: str) -> Optional[str]:
    """Newest committed ``emergency_checkpoint_<step>`` under the root
    (highest step wins) — but only when it is at least as far along as
    the newest committed REGULAR checkpoint. Emergency snapshots are
    never reaped by retention, so a stale one from an old stall can
    outlive hundreds of later interval commits; resuming it would
    silently rewind training that plain auto-resume would have kept.
    Returns None when there is no snapshot worth preferring."""
    if not checkpoint_dir or not os.path.isdir(checkpoint_dir):
        return None
    snaps = _committed_steps(checkpoint_dir, EMERGENCY_PREFIX)
    if not snaps:
        return None
    step, path = max(snaps)
    regular = _committed_steps(checkpoint_dir, "checkpoint_")
    if regular and max(regular)[0] > step:
        print(
            f"supervise: ignoring stale emergency snapshot {path} "
            f"(step {step}) — a newer committed checkpoint exists at "
            f"step {max(regular)[0]}; plain auto-resume keeps more "
            "progress"
        )
        return None
    return path


class Ledger:
    """Append-only JSONL run ledger (one record per supervised exit)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def append(self, record: dict) -> None:
        record = {"ts": time.time(), **record}
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")
            f.flush()
            os.fsync(f.fileno())


def supervise(
    command: List[str],
    checkpoint_dir: str,
    ledger: Ledger,
    max_restarts: int = 100,
    backoff_s: float = 5.0,
    backoff_max_s: float = 300.0,
    flap_window_s: float = 60.0,
    flap_limit: int = 3,
    sleep=time.sleep,
    runner=None,
) -> int:
    """Run ``command`` under exit-class routing. Returns the supervisor's
    own exit code: 0 on a clean child finish, 1 on give-up (flap limit /
    restart budget). ``runner``/``sleep`` are injectable for tests
    (``runner(cmd, env) -> (exit_code,)`` defaults to subprocess)."""

    def default_runner(cmd, env):
        return (subprocess.call(cmd, env=env),)

    runner = runner or default_runner
    attempt = 0
    flap_streak = 0
    delay = backoff_s
    resume_from: Optional[str] = None
    while True:
        attempt += 1
        env = dict(os.environ)
        if resume_from:
            env["TRLX_TPU_RESUME_FROM"] = resume_from
        t0 = time.time()
        (code,) = runner(command, env)
        run_s = time.time() - t0
        exit_class = classify(code)
        record = {
            "attempt": attempt,
            "exit_code": int(code),
            "exit_class": exit_class,
            "run_s": round(run_s, 3),
            "resume_from": resume_from,
        }
        resume_from = None

        if exit_class == "clean":
            ledger.append({**record, "action": "done"})
            print(f"supervise: clean exit after attempt {attempt}")
            return 0

        # flap detection applies to every non-clean exit class: a child
        # that dies within flap_window_s of its own launch, flap_limit
        # times in a row, is not making progress between failures. A
        # long healthy run also resets the crash backoff — an isolated
        # crash after days of progress should not pay backoff
        # accumulated by unrelated failures from the run's start.
        if run_s >= flap_window_s:
            flap_streak = 0
            delay = backoff_s
        else:
            flap_streak += 1
        if flap_streak >= flap_limit:
            ledger.append({
                **record, "action": "gave_up",
                "reason": (
                    f"{flap_streak} consecutive exits within "
                    f"{flap_window_s}s of launch (flap limit "
                    f"{flap_limit}) — restarting cannot help; "
                    "investigate the ledger and the last run's log"
                ),
            })
            print(
                f"supervise: giving up after {attempt} attempts "
                f"({flap_streak} rapid failures in a row)",
                file=sys.stderr,
            )
            return 1
        if attempt >= max_restarts + 1:
            ledger.append({
                **record, "action": "gave_up",
                "reason": f"restart budget exhausted ({max_restarts})",
            })
            print(
                f"supervise: restart budget ({max_restarts}) exhausted",
                file=sys.stderr,
            )
            return 1

        if exit_class == "stalled":
            # hang doctor took the run down (exit 87): the stall is an
            # infra event, not a code bug — restart immediately (no
            # backoff) from the emergency snapshot when one exists
            snap = latest_emergency_snapshot(checkpoint_dir)
            resume_from = snap
            ledger.append({
                **record,
                "action": "resume_snapshot" if snap else "restart",
                "snapshot": snap,
                "backoff_s": 0.0,
            })
            print(
                f"supervise: stalled exit (87); relaunching"
                + (f" from emergency snapshot {snap}" if snap else
                   " (no emergency snapshot found; auto-resume)")
            )
            continue

        # crash: exponential backoff between attempts
        ledger.append({
            **record, "action": "restart", "backoff_s": round(delay, 3),
        })
        print(
            f"supervise: crash (exit {code}); restarting in {delay:.1f}s "
            f"(attempt {attempt + 1})",
            file=sys.stderr,
        )
        sleep(delay)
        delay = min(delay * 2, backoff_max_s)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--checkpoint-dir", default="ckpts",
        help="the run's train.checkpoint_dir — where emergency "
             "snapshots are discovered for stalled-exit routing",
    )
    parser.add_argument(
        "--ledger", default=None,
        help="JSONL run-ledger path (default "
             "<checkpoint-dir>/run_ledger.jsonl)",
    )
    parser.add_argument(
        "--max-restarts", type=int, default=100,
        help="total relaunch budget before giving up",
    )
    parser.add_argument(
        "--backoff", type=float, default=5.0,
        help="initial crash-restart backoff seconds (doubles per "
             "consecutive crash, capped at --backoff-max)",
    )
    parser.add_argument("--backoff-max", type=float, default=300.0)
    parser.add_argument(
        "--flap-window", type=float, default=60.0,
        help="an exit within this many seconds of its own launch "
             "counts toward the flap streak",
    )
    parser.add_argument(
        "--flap-limit", type=int, default=3,
        help="rapid failures in a row before the supervisor gives up",
    )
    parser.add_argument(
        "command", nargs=argparse.REMAINDER,
        help="the training command, after a literal --",
    )
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given (pass it after a literal --)")
    ledger = Ledger(
        args.ledger
        or os.path.join(args.checkpoint_dir, "run_ledger.jsonl")
    )
    return supervise(
        command,
        checkpoint_dir=args.checkpoint_dir,
        ledger=ledger,
        max_restarts=args.max_restarts,
        backoff_s=args.backoff,
        backoff_max_s=args.backoff_max,
        flap_window_s=args.flap_window,
        flap_limit=args.flap_limit,
    )


if __name__ == "__main__":
    sys.exit(main())
