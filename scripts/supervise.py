#!/usr/bin/env python
"""Exit-class-aware run supervisor.

Wraps a training command and routes each exit by its CLASS instead of
blindly relaunching (docs/robustness.md "Exit classes"):

  0   clean finish / handled preemption — honored: the supervisor stops.
  87  stalled (`watchdog.EXIT_STALLED`, the hang doctor) — relaunch
      pointed at the newest emergency snapshot under ``--checkpoint-dir``
      via the ``TRLX_TPU_RESUME_FROM`` env override (api.py); emergency
      snapshots are deliberately invisible to auto-discovery, so without
      this routing a relaunch would silently lose everything after the
      last interval commit. Falls back to a plain relaunch (auto-resume)
      when no snapshot exists.
  *   crash (exception, guardrails abort, OOM-kill) — relaunch with
      exponential backoff (doubling from ``--backoff``, capped at
      ``--backoff-max``), after FLAP DETECTION: ``--flap-limit`` exits
      within ``--flap-window`` seconds of their own launch means the
      process is dying faster than it can make progress (a code bug, not
      an infra event) — the supervisor gives up instead of burning the
      allocation, with a ``gave_up`` ledger entry naming the streak.

Every decision is appended to a machine-readable JSONL RUN LEDGER
(``--ledger``, default ``<checkpoint-dir>/run_ledger.jsonl``): one
record per exit with the attempt number, exit code + class, run wall
seconds, the action taken (``done`` / ``restart`` / ``resume_snapshot``
/ ``gave_up``), the backoff applied and any resume path — what a fleet
dashboard ingests to tell "stalls on host X" from "crash-looping
everywhere".

FLEET MODE (``--worker-cmd``, see :func:`supervise_fleet`): the
positional command is the LEARNER and each ``--worker-cmd`` launches a
rollout-worker slot with per-role routing — workers survive learner
relaunches (the membership-epoch re-attach handshake), a clean worker
exit retires its slot, a crashing one relaunches with per-slot backoff
and flap give-up.

Usage:
    python scripts/supervise.py --checkpoint-dir ckpts -- \
        python examples/ppo_dense_sentiments.py
    python scripts/supervise.py --max-restarts 20 --backoff 5 -- \
        python train.py --config my.yml

Everything after ``--`` is the child command, run as-is with the
current environment (+ ``TRLX_TPU_RESUME_FROM`` when routing a stall).
Tested end to end in child processes: tests/test_supervisor.py.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from trlx_tpu.utils.checkpointing import (  # noqa: E402
    EMERGENCY_PREFIX,
    is_committed,
)
from trlx_tpu.utils.memdoctor import is_degraded_record  # noqa: E402
from trlx_tpu.utils.watchdog import EXIT_STALLED  # noqa: E402

EXIT_CLASSES = {0: "clean", EXIT_STALLED: "stalled"}


def classify(code: int) -> str:
    return EXIT_CLASSES.get(code, "crash")


def _committed_steps(checkpoint_dir: str, prefix: str):
    """(step, path) pairs of committed ``<prefix><step>`` dirs."""
    out = []
    for entry in os.listdir(checkpoint_dir):
        if not entry.startswith(prefix):
            continue
        suffix = entry[len(prefix):]
        if not suffix.isdigit():
            continue
        path = os.path.join(checkpoint_dir, entry)
        if is_committed(path):
            out.append((int(suffix), path))
    return out


def latest_emergency_snapshot(checkpoint_dir: str) -> Optional[str]:
    """Newest committed ``emergency_checkpoint_<step>`` under the root
    (highest step wins) — but only when it is at least as far along as
    the newest committed REGULAR checkpoint. Emergency snapshots are
    never reaped by retention, so a stale one from an old stall can
    outlive hundreds of later interval commits; resuming it would
    silently rewind training that plain auto-resume would have kept.
    Returns None when there is no snapshot worth preferring."""
    if not checkpoint_dir or not os.path.isdir(checkpoint_dir):
        return None
    snaps = _committed_steps(checkpoint_dir, EMERGENCY_PREFIX)
    if not snaps:
        return None
    step, path = max(snaps)
    regular = _committed_steps(checkpoint_dir, "checkpoint_")
    if regular and max(regular)[0] > step:
        print(
            f"supervise: ignoring stale emergency snapshot {path} "
            f"(step {step}) — a newer committed checkpoint exists at "
            f"step {max(regular)[0]}; plain auto-resume keeps more "
            "progress"
        )
        return None
    return path


def read_memory_degrade(checkpoint_dir: str) -> Optional[dict]:
    """The memory-doctor degradation record of the NEWEST committed
    checkpoint (regular or emergency), or None when absent/undegraded.
    A relaunch resumes under this record (trainer.load() adopts it);
    surfacing it in the ledger tells the operator the run is now
    paying recompute/accumulation for HBM headroom — the signal to
    re-size the config instead of relaunching forever."""
    if not checkpoint_dir or not os.path.isdir(checkpoint_dir):
        return None
    ckpts = (
        _committed_steps(checkpoint_dir, "checkpoint_")
        + _committed_steps(checkpoint_dir, EMERGENCY_PREFIX)
    )
    if not ckpts:
        return None
    _, path = max(ckpts)
    try:
        with open(os.path.join(path, "state.json")) as f:
            md = json.load(f).get("memory_degrade")
    except Exception:
        return None
    return md if is_degraded_record(md) else None


class Ledger:
    """Append-only JSONL run ledger (one record per supervised exit).

    When the supervised run keeps a flight-recorder stream
    (``<checkpoint_dir>/flight/`` — trlx_tpu/obs/, on by default),
    every ledger record is MIRRORED into it as a ``supervisor`` event,
    so restarts/stall-resumes/give-ups land in the same correlated
    timeline as the run's own guardrail/OOM/fleet events instead of a
    sixth parallel format."""

    def __init__(self, path: str, flight_dir: str = ""):
        self.path = path
        self.flight_dir = flight_dir
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def append(self, record: dict) -> None:
        record = {"ts": time.time(), **record}
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")
            f.flush()
            os.fsync(f.fileno())
        if self.flight_dir and os.path.isdir(self.flight_dir):
            try:  # best-effort: the ledger stays authoritative
                from trlx_tpu.obs.recorder import append_external

                append_external(
                    self.flight_dir, "supervisor", run="supervisor",
                    **{k: v for k, v in record.items() if k != "ts"},
                )
            except Exception:
                pass


def supervise(
    command: List[str],
    checkpoint_dir: str,
    ledger: Ledger,
    max_restarts: int = 100,
    backoff_s: float = 5.0,
    backoff_max_s: float = 300.0,
    flap_window_s: float = 60.0,
    flap_limit: int = 3,
    sleep=time.sleep,
    runner=None,
) -> int:
    """Run ``command`` under exit-class routing. Returns the supervisor's
    own exit code: 0 on a clean child finish, 1 on give-up (flap limit /
    restart budget). ``runner``/``sleep`` are injectable for tests
    (``runner(cmd, env) -> (exit_code,)`` defaults to subprocess)."""

    def default_runner(cmd, env):
        return (subprocess.call(cmd, env=env),)

    runner = runner or default_runner
    attempt = 0
    flap_streak = 0
    delay = backoff_s
    resume_from: Optional[str] = None
    while True:
        attempt += 1
        env = dict(os.environ)
        if resume_from:
            env["TRLX_TPU_RESUME_FROM"] = resume_from
        t0 = time.time()
        (code,) = runner(command, env)
        run_s = time.time() - t0
        exit_class = classify(code)
        record = {
            "attempt": attempt,
            "exit_code": int(code),
            "exit_class": exit_class,
            "run_s": round(run_s, 3),
            "resume_from": resume_from,
        }
        resume_from = None

        if exit_class == "clean":
            ledger.append({**record, "action": "done"})
            print(f"supervise: clean exit after attempt {attempt}")
            return 0

        # memory doctor: a relaunch resumes under the newest committed
        # checkpoint's degradation record (trainer.load() adopts it) —
        # surface it so the ledger shows the run is trading
        # recompute/accumulation for HBM headroom
        degrade = read_memory_degrade(checkpoint_dir)
        if degrade:
            record["memory_degrade"] = degrade
            print(
                "supervise: checkpoint is memory-doctor DEGRADED "
                f"(grad-accum x{degrade.get('accum_factor', 1)}, pool "
                f"shrinks {degrade.get('pool_shrinks', 0)}, remat "
                f"{degrade.get('remat_policy') or 'unchanged'}) — the "
                "relaunch resumes degraded; re-size the config to clear it"
            )

        # flap detection applies to every non-clean exit class: a child
        # that dies within flap_window_s of its own launch, flap_limit
        # times in a row, is not making progress between failures. A
        # long healthy run also resets the crash backoff — an isolated
        # crash after days of progress should not pay backoff
        # accumulated by unrelated failures from the run's start.
        if run_s >= flap_window_s:
            flap_streak = 0
            delay = backoff_s
        else:
            flap_streak += 1
        if flap_streak >= flap_limit:
            ledger.append({
                **record, "action": "gave_up",
                "reason": (
                    f"{flap_streak} consecutive exits within "
                    f"{flap_window_s}s of launch (flap limit "
                    f"{flap_limit}) — restarting cannot help; "
                    "investigate the ledger and the last run's log"
                ),
            })
            print(
                f"supervise: giving up after {attempt} attempts "
                f"({flap_streak} rapid failures in a row)",
                file=sys.stderr,
            )
            return 1
        if attempt >= max_restarts + 1:
            ledger.append({
                **record, "action": "gave_up",
                "reason": f"restart budget exhausted ({max_restarts})",
            })
            print(
                f"supervise: restart budget ({max_restarts}) exhausted",
                file=sys.stderr,
            )
            return 1

        if exit_class == "stalled":
            # hang doctor took the run down (exit 87): the stall is an
            # infra event, not a code bug — restart immediately (no
            # backoff) from the emergency snapshot when one exists
            snap = latest_emergency_snapshot(checkpoint_dir)
            resume_from = snap
            ledger.append({
                **record,
                "action": "resume_snapshot" if snap else "restart",
                "snapshot": snap,
                "backoff_s": 0.0,
            })
            print(
                f"supervise: stalled exit (87); relaunching"
                + (f" from emergency snapshot {snap}" if snap else
                   " (no emergency snapshot found; auto-resume)")
            )
            continue

        # crash: exponential backoff between attempts
        ledger.append({
            **record, "action": "restart", "backoff_s": round(delay, 3),
        })
        print(
            f"supervise: crash (exit {code}); restarting in {delay:.1f}s "
            f"(attempt {attempt + 1})",
            file=sys.stderr,
        )
        sleep(delay)
        delay = min(delay * 2, backoff_max_s)


def supervise_fleet(
    learner_cmd: List[str],
    worker_cmds: List[List[str]],
    checkpoint_dir: str,
    ledger: Ledger,
    max_restarts: int = 100,
    backoff_s: float = 5.0,
    backoff_max_s: float = 300.0,
    flap_window_s: float = 60.0,
    flap_limit: int = 3,
    poll_s: float = 0.2,
    hub_cmd: Optional[List[str]] = None,
) -> int:
    """Fleet mode (``--worker-cmd``): the learner and N rollout workers
    — plus, with ``--hub-cmd``, an external transport hub — run as
    sibling child processes with PER-ROLE exit-class routing.

    learner   routed exactly like :func:`supervise` — clean stop ends
              the fleet (workers are signalled, then terminated as the
              backstop), stalled (87) relaunches from the newest
              emergency snapshot, crash relaunches with backoff + flap
              give-up. Workers are deliberately left RUNNING across a
              learner relaunch: the relaunched learner bumps the
              membership epoch and the surviving workers re-register
              (the re-attach handshake), so a learner stall never costs
              the fleet's warm compiles.
    worker    exit 0 is honored (the learner's clean-finish flag, or a
              worker-side ``max_chunks`` budget) — the slot is not
              relaunched. Any other exit is a crash: relaunch with
              per-slot doubling backoff; ``flap_limit`` rapid failures
              in a row retires the SLOT (ledger ``gave_up``) instead of
              the run — the learner degrades below ``fleet.min_workers``
              on its own if too many slots retire.
    hub       (``--hub-cmd``, e.g. ``python -m trlx_tpu.exp.net --port
              9123`` with the run's transport spec at ``host_hub:
              false``) the load-bearing message bus: ANY exit while the
              run lives is an outage, so the routing is
              relaunch-first. A clean exit (0 — operator SIGTERM)
              relaunches immediately; a crash relaunches with doubling
              backoff. Clients are built to ride it out: reconnect
              backoff+jitter on every rpc, workers re-register on their
              next beat, the learner re-dispatches and re-publishes
              into the empty hub. But ``flap_limit`` rapid hub deaths
              in a row means nothing can talk to anything — the whole
              fleet stops (ledger ``gave_up``, exit 1), unlike a
              retired worker slot. The hub is launched FIRST and
              stopped LAST, so relaunching roles always find the bus.

    Every decision lands in the same JSONL ledger with a ``role`` field
    (``learner`` / ``worker-<i>`` / ``hub``)."""
    import signal

    t_now = time.time
    learner: Optional[subprocess.Popen] = None
    workers: List[Optional[subprocess.Popen]] = [None] * len(worker_cmds)
    hub: Optional[subprocess.Popen] = None
    wstate = [
        {"streak": 0, "delay": backoff_s, "next_launch": 0.0,
         "launched": 0.0, "retired": False, "attempt": 0}
        for _ in worker_cmds
    ]
    l_attempt = 0
    l_streak = 0
    l_delay = backoff_s
    l_next_launch = 0.0
    l_launched = 0.0
    h_attempt = 0
    h_streak = 0
    h_delay = backoff_s
    h_next_launch = 0.0
    h_launched = 0.0
    resume_from: Optional[str] = None

    def spawn_learner():
        nonlocal learner, l_attempt, l_launched
        env = dict(os.environ)
        if resume_from:
            env["TRLX_TPU_RESUME_FROM"] = resume_from
        l_attempt += 1
        l_launched = t_now()
        learner = subprocess.Popen(learner_cmd, env=env)

    def spawn_worker(i: int):
        workers[i] = subprocess.Popen(worker_cmds[i], env=dict(os.environ))
        wstate[i]["launched"] = t_now()
        wstate[i]["attempt"] += 1

    def spawn_hub():
        nonlocal hub, h_attempt, h_launched
        h_attempt += 1
        h_launched = t_now()
        hub = subprocess.Popen(hub_cmd, env=dict(os.environ))

    def stop_proc(proc, sig=signal.SIGTERM, grace_s: float = 10.0):
        if proc is None:
            return
        if proc.poll() is None:
            try:
                proc.send_signal(sig)
            except OSError:
                pass
            deadline = t_now() + grace_s
            while proc.poll() is None and t_now() < deadline:
                time.sleep(poll_s)
            if proc.poll() is None:
                proc.kill()
        proc.wait()  # reap — an embedding caller must not leak zombies

    def stop_workers(sig=signal.SIGTERM, grace_s: float = 10.0):
        for proc in workers:
            if proc is not None and proc.poll() is None:
                try:
                    proc.send_signal(sig)
                except OSError:
                    pass
        deadline = t_now() + grace_s
        for proc in workers:
            if proc is None:
                continue
            while proc.poll() is None and t_now() < deadline:
                time.sleep(poll_s)
            if proc.poll() is None:
                proc.kill()
            proc.wait()  # reap — an embedding caller must not leak zombies

    def stop_fleet():
        # workers first (they need the hub to observe the shutdown
        # flag), hub last
        stop_workers()
        stop_proc(hub)

    try:
        if hub_cmd:
            spawn_hub()
        spawn_learner()
        for i in range(len(worker_cmds)):
            spawn_worker(i)
        while True:
            time.sleep(poll_s)
            # -- hub routing (the message bus everyone needs) -----------
            if hub_cmd:
                hcode = hub.poll() if hub is not None else None
                if hcode is not None:
                    run_s = t_now() - h_launched
                    record = {
                        "role": "hub", "attempt": h_attempt,
                        "exit_code": int(hcode),
                        "exit_class": classify(hcode),
                        "run_s": round(run_s, 3),
                    }
                    hub = None
                    if run_s >= flap_window_s:
                        h_streak, h_delay = 0, backoff_s
                    else:
                        h_streak += 1
                    if h_streak >= flap_limit:
                        ledger.append({
                            **record, "action": "gave_up",
                            "reason": (
                                f"{h_streak} rapid hub deaths in a row "
                                "— the bus is load-bearing; stopping "
                                "the whole fleet"
                            ),
                        })
                        print(
                            "supervise: hub flapping — stopping learner "
                            "+ workers", file=sys.stderr,
                        )
                        stop_proc(learner)
                        learner = None
                        stop_workers()
                        return 1
                    if hcode == 0:
                        # a deliberate stop of a load-bearing role is
                        # still an outage mid-run: relaunch immediately
                        ledger.append({
                            **record, "action": "restart",
                            "backoff_s": 0.0,
                        })
                        h_next_launch = t_now()
                    else:
                        ledger.append({
                            **record, "action": "restart",
                            "backoff_s": round(h_delay, 3),
                        })
                        h_next_launch = t_now() + h_delay
                        h_delay = min(h_delay * 2, backoff_max_s)
                    print(
                        f"supervise: hub exit {hcode}; relaunching "
                        "(clients reconnect + re-register)",
                        file=sys.stderr,
                    )
                if hub is None and t_now() >= h_next_launch:
                    spawn_hub()
            # -- learner routing (the run's fate) -----------------------
            code = learner.poll() if learner is not None else None
            if code is not None:
                run_s = t_now() - l_launched
                exit_class = classify(code)
                record = {
                    "role": "learner", "attempt": l_attempt,
                    "exit_code": int(code), "exit_class": exit_class,
                    "run_s": round(run_s, 3), "resume_from": resume_from,
                }
                resume_from = None
                learner = None
                if exit_class == "clean":
                    ledger.append({**record, "action": "done"})
                    print("supervise: learner finished cleanly; "
                          "stopping the worker fleet")
                    stop_fleet()
                    return 0
                if run_s >= flap_window_s:
                    l_streak, l_delay = 0, backoff_s
                else:
                    l_streak += 1
                if l_streak >= flap_limit or l_attempt >= max_restarts + 1:
                    reason = (
                        f"{l_streak} rapid learner failures in a row"
                        if l_streak >= flap_limit
                        else f"restart budget exhausted ({max_restarts})"
                    )
                    ledger.append(
                        {**record, "action": "gave_up", "reason": reason}
                    )
                    print(f"supervise: giving up ({reason}); stopping "
                          "the worker fleet", file=sys.stderr)
                    stop_fleet()
                    return 1
                if exit_class == "stalled":
                    resume_from = latest_emergency_snapshot(checkpoint_dir)
                    ledger.append({
                        **record,
                        "action": "resume_snapshot" if resume_from
                        else "restart",
                        "snapshot": resume_from, "backoff_s": 0.0,
                    })
                    l_next_launch = t_now()
                else:
                    ledger.append({
                        **record, "action": "restart",
                        "backoff_s": round(l_delay, 3),
                    })
                    l_next_launch = t_now() + l_delay
                    l_delay = min(l_delay * 2, backoff_max_s)
                print(
                    f"supervise: learner exit {code} ({exit_class}); "
                    "relaunching with the worker fleet left attached",
                    file=sys.stderr,
                )
            if learner is None and t_now() >= l_next_launch:
                spawn_learner()
            # -- worker routing (per-slot) ------------------------------
            for i, proc in enumerate(workers):
                st = wstate[i]
                if proc is not None:
                    wcode = proc.poll()
                    if wcode is None:
                        continue
                    run_s = t_now() - st["launched"]
                    workers[i] = None
                    record = {
                        "role": f"worker-{i}", "attempt": st["attempt"],
                        "exit_code": int(wcode),
                        "exit_class": "clean" if wcode == 0 else "crash",
                        "run_s": round(run_s, 3),
                    }
                    if wcode == 0:
                        # clean worker exit = the learner's shutdown
                        # flag or a worker-side budget; not an outage
                        ledger.append({**record, "action": "done"})
                        st["retired"] = True
                        continue
                    if run_s >= flap_window_s:
                        st["streak"], st["delay"] = 0, backoff_s
                    else:
                        st["streak"] += 1
                    if st["streak"] >= flap_limit:
                        ledger.append({
                            **record, "action": "gave_up",
                            "reason": (
                                f"{st['streak']} rapid failures in a "
                                "row — slot retired (the learner "
                                "degrades below fleet.min_workers on "
                                "its own)"
                            ),
                        })
                        st["retired"] = True
                        continue
                    ledger.append({
                        **record, "action": "restart",
                        "backoff_s": round(st["delay"], 3),
                    })
                    st["next_launch"] = t_now() + st["delay"]
                    st["delay"] = min(st["delay"] * 2, backoff_max_s)
                elif not st["retired"] and t_now() >= st["next_launch"]:
                    spawn_worker(i)
    except KeyboardInterrupt:
        print("supervise: interrupted — stopping learner + workers",
              file=sys.stderr)
        if learner is not None and learner.poll() is None:
            learner.terminate()
            try:
                learner.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                learner.kill()
                learner.wait()
        stop_fleet()
        return 130
    except BaseException:
        # a failed spawn (bad worker command), a full-disk ledger write,
        # anything unexpected: never leave the fleet running unmanaged
        print("supervise: internal error — stopping learner + workers",
              file=sys.stderr)
        if learner is not None and learner.poll() is None:
            learner.kill()
            learner.wait()
        stop_fleet()
        raise


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--checkpoint-dir", default="ckpts",
        help="the run's train.checkpoint_dir — where emergency "
             "snapshots are discovered for stalled-exit routing",
    )
    parser.add_argument(
        "--ledger", default=None,
        help="JSONL run-ledger path (default "
             "<checkpoint-dir>/run_ledger.jsonl)",
    )
    parser.add_argument(
        "--max-restarts", type=int, default=100,
        help="total relaunch budget before giving up",
    )
    parser.add_argument(
        "--backoff", type=float, default=5.0,
        help="initial crash-restart backoff seconds (doubles per "
             "consecutive crash, capped at --backoff-max)",
    )
    parser.add_argument("--backoff-max", type=float, default=300.0)
    parser.add_argument(
        "--flap-window", type=float, default=60.0,
        help="an exit within this many seconds of its own launch "
             "counts toward the flap streak",
    )
    parser.add_argument(
        "--flap-limit", type=int, default=3,
        help="rapid failures in a row before the supervisor gives up",
    )
    parser.add_argument(
        "--worker-cmd", action="append", default=[],
        help="FLEET MODE: a rollout-worker command (shell-quoted "
             "string; '{i}' expands to the slot index), repeatable — "
             "one slot per flag. The positional command becomes the "
             "LEARNER; workers get per-role exit routing (clean = "
             "retire slot, crash = per-slot backoff + flap give-up) "
             "and survive learner relaunches for the membership-epoch "
             "re-attach handshake",
    )
    parser.add_argument(
        "--workers", type=int, default=0,
        help="with a single --worker-cmd: replicate it into this many "
             "slots (each formatting '{i}' with its index)",
    )
    parser.add_argument(
        "--hub-cmd", default=None,
        help="FLEET MODE: an external transport-hub command (e.g. "
             "\"python -m trlx_tpu.exp.net --port 9123\") run as its "
             "own supervised role — pair with a run config whose "
             "transport spec says host_hub: false. Any hub exit "
             "mid-run is an outage: clean exits relaunch immediately, "
             "crashes with doubling backoff, and a flapping hub stops "
             "the whole fleet (it is load-bearing, unlike a worker "
             "slot)",
    )
    parser.add_argument(
        "--flight-dir", default="",
        help="flight-recorder dir to mirror ledger records into as "
             "'supervisor' events (default <checkpoint-dir>/flight; "
             "point it at a custom train.obs.dir when the run uses "
             "one). Mirroring is best-effort and skipped when the dir "
             "does not exist",
    )
    parser.add_argument(
        "command", nargs=argparse.REMAINDER,
        help="the training command, after a literal --",
    )
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given (pass it after a literal --)")
    ledger = Ledger(
        args.ledger
        or os.path.join(args.checkpoint_dir, "run_ledger.jsonl"),
        flight_dir=(
            args.flight_dir
            or os.path.join(args.checkpoint_dir, "flight")
        ),
    )
    if args.hub_cmd and not args.worker_cmd:
        parser.error("--hub-cmd is a fleet-mode role; add --worker-cmd")
    if args.worker_cmd:
        import shlex

        worker_cmds = list(args.worker_cmd)
        if args.workers > 0:
            if len(worker_cmds) != 1:
                parser.error(
                    "--workers N replicates exactly one --worker-cmd"
                )
            worker_cmds = worker_cmds * args.workers
        return supervise_fleet(
            command,
            # plain replace, not str.format: a literal brace in the
            # worker command (JSON overrides, shell syntax) must pass
            # through — only the documented '{i}' token expands
            [shlex.split(cmd.replace("{i}", str(i)))
             for i, cmd in enumerate(worker_cmds)],
            checkpoint_dir=args.checkpoint_dir,
            ledger=ledger,
            max_restarts=args.max_restarts,
            backoff_s=args.backoff,
            backoff_max_s=args.backoff_max,
            flap_window_s=args.flap_window,
            flap_limit=args.flap_limit,
            hub_cmd=shlex.split(args.hub_cmd) if args.hub_cmd else None,
        )
    return supervise(
        command,
        checkpoint_dir=args.checkpoint_dir,
        ledger=ledger,
        max_restarts=args.max_restarts,
        backoff_s=args.backoff,
        backoff_max_s=args.backoff_max,
        flap_window_s=args.flap_window,
        flap_limit=args.flap_limit,
    )


if __name__ == "__main__":
    sys.exit(main())
