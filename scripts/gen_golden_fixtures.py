"""Generate golden-numerics fixtures from the reference implementation.

Runs the reference's pure torch functions (GAE, PPO loss, ILQL loss,
whiten, RunningMoments, logprobs_of_labels — SURVEY.md §7 "hard parts")
on seeded inputs and saves the tensors to tests/golden/*.npz.
tests/test_golden.py then asserts the trlx_tpu ops reproduce them.

This script only runs in the build environment (it imports from
/root/reference); the committed .npz fixtures are what CI uses. The
reference's optional deps (torchtyping, deepspeed) are stubbed with
minimal shims so the pure functions import — no reference code is
vendored or copied.
"""

import importlib.machinery
import sys
import types

import numpy as np
import torch

REFERENCE = "/root/reference"


def _install_shims():
    if "torchtyping" not in sys.modules:
        shim = types.ModuleType("torchtyping")

        class _TensorType:
            def __class_getitem__(cls, item):
                return torch.Tensor

        shim.TensorType = _TensorType
        sys.modules["torchtyping"] = shim
    # the reference's config modules import trainer modules at package
    # import time, which drag in cluster-only deps; stub what's missing
    for name in ("deepspeed", "ray", "ray.air", "ray.air.session", "ray.tune",
                 "tritonclient", "tritonclient.grpc", "wandb"):
        if name not in sys.modules:
            try:
                __import__(name)
            except ImportError:
                mod = types.ModuleType(name)
                mod.zero = types.SimpleNamespace(GatheredParameters=None)
                # a None __spec__ breaks importlib.util.find_spec probes
                # (accelerate runs one on import)
                mod.__spec__ = importlib.machinery.ModuleSpec(name, loader=None)
                sys.modules[name] = mod


def main(out_dir: str):
    import os

    _install_shims()
    sys.path.insert(0, REFERENCE)
    from trlx.models.modeling_ilql import ILQLConfig
    from trlx.models.modeling_ppo import PPOConfig
    from trlx.utils.modeling import RunningMoments, logprobs_of_labels, whiten

    os.makedirs(out_dir, exist_ok=True)
    torch.manual_seed(0)
    rng = np.random.default_rng(0)

    # --- whiten -----------------------------------------------------------
    xs = rng.normal(size=(8, 16)).astype(np.float32)
    np.savez(
        os.path.join(out_dir, "whiten.npz"),
        xs=xs,
        shifted=whiten(torch.tensor(xs), shift_mean=True).numpy(),
        unshifted=whiten(torch.tensor(xs), shift_mean=False).numpy(),
    )

    # --- logprobs_of_labels ----------------------------------------------
    logits = rng.normal(size=(4, 10, 50)).astype(np.float32) * 3
    labels = rng.integers(0, 50, size=(4, 10))
    np.savez(
        os.path.join(out_dir, "logprobs.npz"),
        logits=logits,
        labels=labels,
        # reference convention: logits[:, :-1] vs labels[:, 1:]
        out=logprobs_of_labels(
            torch.tensor(logits)[:, :-1], torch.tensor(labels)[:, 1:]
        ).numpy(),
    )

    # --- RunningMoments ---------------------------------------------------
    rm = RunningMoments()
    batches = [rng.normal(loc=i, size=(32,)).astype(np.float32) * (1 + i) for i in range(4)]
    means, stds, run_means, run_stds = [], [], [], []
    for b in batches:
        m, s = rm.update(torch.tensor(b))
        # snapshot as floats: rm.mean becomes a tensor that later updates
        # mutate in place, so storing the object records only final values
        means.append(float(m))
        stds.append(float(s))
        run_means.append(float(rm.mean))
        run_stds.append(float(rm.std))
    np.savez(
        os.path.join(out_dir, "running_moments.npz"),
        batches=np.stack(batches),
        batch_means=np.asarray(means, np.float32),
        batch_stds=np.asarray(stds, np.float32),
        running_means=np.asarray(run_means, np.float32),
        running_stds=np.asarray(run_stds, np.float32),
    )

    # --- PPO GAE + loss ---------------------------------------------------
    cfg = PPOConfig(
        name="PPOConfig", ppo_epochs=4, num_rollouts=128, chunk_size=128,
        init_kl_coef=0.05, target=6.0, horizon=10000, gamma=0.99, lam=0.95,
        cliprange=0.2, cliprange_value=0.2, vf_coef=1.0,
        scale_reward=None, ref_mean=None, ref_std=None,
        cliprange_reward=10.0, gen_kwargs={},
    )
    B, T = 6, 12
    values_t = rng.normal(size=(B, T)).astype(np.float32)
    rewards_t = rng.normal(size=(B, T)).astype(np.float32) * 0.1
    adv, ret = cfg.get_advantages_and_returns(
        torch.tensor(values_t), torch.tensor(rewards_t), T, use_whitening=True
    )
    adv_nw, ret_nw = cfg.get_advantages_and_returns(
        torch.tensor(values_t), torch.tensor(rewards_t), T, use_whitening=False
    )

    logprobs = rng.normal(size=(B, T)).astype(np.float32) * 0.5 - 2
    old_logprobs = logprobs + rng.normal(size=(B, T)).astype(np.float32) * 0.1
    new_values = values_t + rng.normal(size=(B, T)).astype(np.float32) * 0.3
    mask = (rng.random((B, T)) > 0.2).astype(np.float32)
    mask[:, 0] = 1.0
    loss, stats = cfg.loss(
        logprobs=torch.tensor(logprobs),
        values=torch.tensor(new_values),
        old_logprobs=torch.tensor(old_logprobs),
        old_values=torch.tensor(values_t),
        advantages=adv,
        returns=ret,
        mask=torch.tensor(mask),
    )
    np.savez(
        os.path.join(out_dir, "ppo.npz"),
        values=values_t,
        rewards=rewards_t,
        advantages=adv.numpy(),
        returns=ret.numpy(),
        advantages_nw=adv_nw.numpy(),
        returns_nw=ret_nw.numpy(),
        logprobs=logprobs,
        old_logprobs=old_logprobs,
        new_values=new_values,
        mask=mask,
        loss=float(loss),
        **{
            "stat_" + k.replace("/", "__"): np.float32(v)
            for k, v in stats.items()
            if np.ndim(v) == 0
        },
    )

    # --- ILQL loss --------------------------------------------------------
    from trlx.data.ilql_types import ILQLBatch

    icfg = ILQLConfig(
        name="ilqlconfig", tau=0.7, gamma=0.99, cql_scale=0.1, awac_scale=1.0,
        alpha=0.995, beta=0.5, steps_for_target_q_sync=5, two_qs=True,
        gen_kwargs={},
    )
    B, A, V = 4, 6, 30  # batch, actions, vocab; states = A + 1
    S = A + 1
    T_in = S + 1
    input_ids = rng.integers(0, V, size=(B, T_in))
    attn = np.ones((B, T_in), np.int64)
    logits_i = (rng.normal(size=(B, A, V)) * 2).astype(np.float32)
    qs_i = [(rng.normal(size=(B, A, V))).astype(np.float32) for _ in range(2)]
    tqs_i = [(rng.normal(size=(B, A, V))).astype(np.float32) for _ in range(2)]
    vs_i = rng.normal(size=(B, S, 1)).astype(np.float32)
    rewards_i = (rng.random((B, A)) > 0.8).astype(np.float32)
    actions_ixs = np.tile(np.arange(A), (B, 1))
    states_ixs = np.tile(np.arange(S), (B, 1))
    dones = np.ones((B, S), np.int64)
    dones[:, -1] = 0
    batch = ILQLBatch(
        input_ids=torch.tensor(input_ids),
        attention_mask=torch.tensor(attn),
        rewards=torch.tensor(rewards_i),
        states_ixs=torch.tensor(states_ixs),
        actions_ixs=torch.tensor(actions_ixs),
        dones=torch.tensor(dones),
    )
    loss_i, stats_i = icfg.loss(
        (
            torch.tensor(logits_i),
            (
                tuple(torch.tensor(q) for q in qs_i),
                tuple(torch.tensor(q) for q in tqs_i),
                torch.tensor(vs_i),
            ),
        ),
        batch,
    )
    np.savez(
        os.path.join(out_dir, "ilql.npz"),
        input_ids=input_ids,
        logits=logits_i,
        q0=qs_i[0], q1=qs_i[1], tq0=tqs_i[0], tq1=tqs_i[1],
        vs=vs_i,
        rewards=rewards_i,
        actions_ixs=actions_ixs,
        states_ixs=states_ixs,
        dones=dones,
        loss=float(loss_i),
        **{
            "stat_" + k.replace("/", "__"): np.float32(v)
            for k, v in stats_i.items()
            if np.ndim(np.asarray(v)) == 0
        },
    )

    print("wrote fixtures to", out_dir)


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "tests/golden")
