#!/usr/bin/env python
"""Offline checkpoint validator.

Validates a checkpoint directory (or a whole checkpoint root) without
touching accelerators: commit marker present and well-formed, orbax
`state/` tree present, `state.json` parses and carries a step counter,
`hf_model/` deploy export present, and — when the checkpoint carries an
`integrity.json` manifest (every post-elastic commit does) — every
hashed file matches its sha256, with a per-file mismatch report when
not. With `--deep` the orbax tree is actually restored (CPU) and every
array leaf is checked finite. `--write-manifest` BACKFILLS integrity
manifests for pre-elastic checkpoints (committed directories lacking
one), so old runs get quarantine protection on their next resume.

Hang-doctor EMERGENCY snapshots (``emergency_checkpoint_<step>``,
``emergency: true`` in the COMMIT marker) are reported distinctly —
they are resumable training state persisted from the host-RAM shadow
while the run was wedged, not health-gated commits — and
``--write-manifest`` refuses to bless them.

Usage:
    python scripts/verify_ckpt.py ckpts/checkpoint_0042 [--deep]
    python scripts/verify_ckpt.py ckpts            # scan every checkpoint_*/best_checkpoint
    python scripts/verify_ckpt.py ckpts --write-manifest
Exit code 0 = everything checked out; 1 = at least one problem.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# validator must run on build/login nodes with no TPU attached
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from trlx_tpu.utils.memdoctor import is_degraded_record  # noqa: E402
from trlx_tpu.utils.checkpointing import (  # noqa: E402
    COMMIT_MARKER,
    EMERGENCY_PREFIX,
    INTEGRITY_MANIFEST,
    QUARANTINE_SUFFIX,
    STALL_REPORT_FILE,
    check_cursor_invariants,
    is_committed,
    is_emergency,
    verify_integrity,
    write_integrity_manifest,
)


def check_one(directory: str, deep: bool = False) -> list:
    """Return a list of problem strings (empty = valid)."""
    problems = []
    if not os.path.isdir(directory):
        return [f"{directory}: not a directory"]

    marker = os.path.join(directory, COMMIT_MARKER)
    if not is_committed(directory):
        problems.append(
            f"{directory}: no {COMMIT_MARKER} marker (torn write from a "
            "mid-save preemption, or a pre-fault-tolerance checkpoint) — "
            "auto-resume will skip it"
        )
    else:
        try:
            with open(marker) as f:
                json.load(f)
        except Exception as e:
            problems.append(f"{marker}: marker unreadable ({e})")

    state_dir = os.path.join(directory, "state")
    if not os.path.isdir(state_dir):
        problems.append(
            f"{directory}: no state/ tree (saved with save_optimizer=false? "
            "resume would restore params only via hf_model)"
        )

    state_fp = os.path.join(directory, "state.json")
    if not os.path.isfile(state_fp):
        problems.append(
            f"{directory}: no state.json — a resume cannot recover "
            "iter_count/best_reward/PRNG and restarts counters from 0"
        )
    else:
        try:
            with open(state_fp) as f:
                state = json.load(f)
            if "iter_count" not in state:
                problems.append(f"{state_fp}: missing iter_count")
            # experience transport: report the consumer cursor /
            # staleness fields and FAIL LOUDLY on the torn-commit
            # invariant (cursor past the committed prompt-stream
            # position — see checkpointing.check_cursor_invariants)
            eq = state.get("exp_queue")
            if isinstance(eq, dict):
                print(
                    f"NOTE  {directory}: experience-transport state — "
                    f"epoch {eq.get('epoch')}, consumer cursor "
                    f"{eq.get('cursor')}, policy_version "
                    f"{eq.get('policy_version')}, staleness mode "
                    f"{eq.get('staleness_mode', 'reject')!r} (prompt "
                    f"cursor {state.get('prompt_batches_consumed')})"
                )
            # rollout fleet (trlx_tpu/fleet/): report the persisted
            # membership epoch + broadcast version; the torn-commit
            # invariant (exp cursor referencing a policy version the
            # committed snapshot never broadcast) fails loudly through
            # check_cursor_invariants below
            fleet = state.get("fleet")
            if isinstance(fleet, dict):
                bver = fleet.get("broadcast_version")
                print(
                    f"NOTE  {directory}: rollout-fleet state — "
                    f"membership epoch {fleet.get('membership_epoch')} "
                    "(a relaunched learner re-attaches by bumping past "
                    "it), broadcast policy version "
                    f"{'none published' if bver in (None, -1) else bver}"
                    f", publish cadence {fleet.get('broadcast_every', 1)}"
                )
            # memory doctor (utils/memdoctor.py): report the persisted
            # degradation level — a resume of this checkpoint under a
            # config with the doctor disabled fails loudly in
            # trainer.load() (the original sizes already OOMed)
            md = state.get("memory_degrade")
            if is_degraded_record(md):
                print(
                    f"NOTE  {directory}: memory-doctor DEGRADED state — "
                    f"pool shrinks {md.get('pool_shrinks', 0)}, grad-accum "
                    f"x{md.get('accum_factor', 1)}, remat "
                    f"{md.get('remat_policy') or 'unchanged'} "
                    f"({len(md.get('events', []))} OOM events recorded). "
                    "Resuming requires train.memory.enabled (adopts the "
                    "degradation) or train.memory.accept_undegrade "
                    "(asserts the original sizes fit now)"
                )
            # guardrail trip tail (trlx_tpu/obs/ persists a bounded
            # tail inside the atomic commit so the flight recorder's
            # post-resume stream isn't amnesiac): report what tripped
            # before this checkpoint was committed
            trips = state.get("guardrail_trips")
            if isinstance(trips, list) and trips:
                counts = {}
                for s in trips:
                    counts[str(s)] = counts.get(str(s), 0) + 1
                print(
                    f"NOTE  {directory}: guardrail trip tail — "
                    f"{len(trips)} trips ("
                    + ", ".join(
                        f"{k} x{v}" for k, v in sorted(counts.items())
                    )
                    + f"); last: {', '.join(map(str, trips[-6:]))}"
                )
            obs_state = state.get("obs")
            if isinstance(obs_state, dict) and obs_state.get("run_id"):
                print(
                    f"NOTE  {directory}: flight-recorder run "
                    f"{obs_state['run_id']} — cycle "
                    f"{obs_state.get('cycle_count')}, "
                    f"{obs_state.get('total_samples')} samples in "
                    f"{round(float(obs_state.get('total_wall_s', 0.0)), 1)}s"
                    " (render the stream with scripts/flight_report.py)"
                )
            problems.extend(
                f"{state_fp}: {p}" for p in check_cursor_invariants(state)
            )
        except Exception as e:
            problems.append(f"{state_fp}: unparseable ({e})")

    if is_emergency(directory):
        # hang-doctor snapshot: written from the host-RAM shadow while
        # the device was wedged — resumable training state, but not a
        # health-gated commit and never a deploy artifact (no hf_model/)
        report = os.path.join(directory, STALL_REPORT_FILE)
        why = ""
        if os.path.isfile(report):
            try:
                with open(report) as f:
                    why = f" — stall: {json.load(f).get('summary', '?')}"
            except Exception:
                pass
        print(
            f"NOTE  {directory}: EMERGENCY snapshot (emergency=true in "
            f"its {COMMIT_MARKER} marker{why}). Written by the hang "
            "doctor from the last health-gated state; resume it via an "
            "explicit train.resume_from_checkpoint path after reading "
            f"{STALL_REPORT_FILE}"
        )
    elif not os.path.isdir(os.path.join(directory, "hf_model")):
        problems.append(f"{directory}: no hf_model/ deploy export")

    status, mismatches = verify_integrity(directory)
    if status == "corrupt":
        problems.append(
            f"{directory}: integrity manifest mismatch — {len(mismatches)} "
            "leaves differ from the committed sha256s (a resume would "
            "quarantine this checkpoint):"
        )
        problems.extend(f"  {directory}: {m}" for m in mismatches)
    elif status == "no-manifest":
        print(
            f"NOTE  {directory}: no {INTEGRITY_MANIFEST} (pre-elastic "
            "commit) — backfill with --write-manifest for quarantine "
            "protection"
        )

    if deep and os.path.isdir(state_dir):
        try:
            import numpy as np
            import orbax.checkpoint as ocp

            tree = ocp.PyTreeCheckpointer().restore(os.path.abspath(state_dir))
            import jax

            bad = [
                path
                for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
                if np.issubdtype(np.asarray(leaf).dtype, np.floating)
                and not np.all(np.isfinite(np.asarray(leaf)))
            ]
            if bad:
                problems.append(
                    f"{state_dir}: non-finite values in {len(bad)} leaves "
                    f"(first: {bad[0]})"
                )
        except Exception as e:
            problems.append(f"{state_dir}: orbax restore failed ({e})")

    return problems


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("path", help="checkpoint dir or checkpoint root")
    parser.add_argument(
        "--deep", action="store_true",
        help="restore the orbax state tree and check every leaf finite",
    )
    parser.add_argument(
        "--write-manifest", action="store_true",
        help="backfill integrity.json for committed checkpoints that "
             "lack one (pre-elastic saves); existing manifests are "
             "left untouched",
    )
    args = parser.parse_args(argv)

    path = os.path.abspath(args.path)
    # a root is a directory that itself holds checkpoint_*/best_checkpoint
    entries = sorted(os.listdir(path)) if os.path.isdir(path) else []
    children = [
        os.path.join(path, e)
        for e in entries
        if (
            e.startswith("checkpoint_")
            or e == "best_checkpoint"
            or e.startswith(EMERGENCY_PREFIX)
        )
        and QUARANTINE_SUFFIX not in e  # quarantined = known-corrupt, NOTEd below
    ]
    for entry in entries:
        if entry.startswith("tmp_old_") or QUARANTINE_SUFFIX not in entry:
            continue
        print(
            f"NOTE  {os.path.join(path, entry)}: QUARANTINED checkpoint "
            "(failed integrity verification on a past load; kept for "
            "postmortem, skipped by discovery)"
        )
    if children:
        targets = children
    elif any(
        os.path.exists(os.path.join(path, p))
        for p in (COMMIT_MARKER, "state", "state.json", "hf_model")
    ):
        targets = [path]  # a single checkpoint directory
    else:
        # a checkpoint ROOT with nothing committed yet (young run, or
        # only tmp_/logs/quarantine entries): that's a clean state, not
        # corruption — don't validate the root as if it were a
        # checkpoint
        print(f"OK    {path}: no committed checkpoints to validate")
        return 0

    rc = 0
    for entry in entries:
        if entry.startswith("tmp_old_"):
            print(
                f"NOTE  {os.path.join(path, entry)}: aside copy from an "
                "interrupted re-commit — the previous good version of "
                f"'{entry[len('tmp_old_'):].rsplit('.', 1)[0]}'; restore "
                "it by renaming if the final copy is missing/torn"
            )
    if args.write_manifest and not args.deep:
        print(
            "NOTE  --write-manifest without --deep: the manifest will "
            "bless whatever bytes are on disk; --deep first restores "
            "the orbax tree and checks every leaf finite, so latent "
            "corruption cannot be certified as verified"
        )
    for target in targets:
        problems = check_one(target, deep=args.deep)
        if args.write_manifest and is_emergency(target):
            # never bless an emergency snapshot: it was persisted while
            # the run was wedged, outside the health-gated commit
            # protocol — a backfilled manifest would certify it as a
            # verified commit, which it is not (its own commit wrote a
            # manifest already; a MISSING one means the write was cut
            # short and the snapshot deserves suspicion, not a stamp)
            print(
                f"NOTE  {target}: EMERGENCY snapshot — refusing "
                "--write-manifest (not a health-gated commit)"
            )
        elif (
            args.write_manifest and is_committed(target) and not problems
            and not os.path.isfile(os.path.join(target, INTEGRITY_MANIFEST))
        ):
            # backfill ONLY when every other check (incl. --deep when
            # given) passed — a manifest over a checkpoint that already
            # fails validation would certify corruption as verified
            write_integrity_manifest(target)
            print(f"WROTE {os.path.join(target, INTEGRITY_MANIFEST)}")
        if problems:
            rc = 1
            for p in problems:
                print(f"FAIL  {p}")
        else:
            step = "?"
            try:
                with open(os.path.join(target, "state.json")) as f:
                    step = json.load(f).get("iter_count", "?")
            except Exception:
                pass
            print(f"OK    {target} (iter_count={step})")
    return rc


if __name__ == "__main__":
    sys.exit(main())
