"""Warm the persistent XLA compile cache for every bench section.

Run this AFTER the last code change that touches bench.py or any model
code it drives: the compile-cache key covers the lowered module
(including source locations of traced functions), so an edit to bench.py
invalidates the entries its sections wrote. With a warm cache every
bench section fits its reserved time slice with minutes to spare; cold,
the 1.3B sections alone can blow the whole budget (the r04 failure
mode — see bench.SECTIONS).

Each section runs in its own process (same as bench.main) with a
generous timeout, and results are printed so a warm run doubles as a
sanity check of the numbers.
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402

if __name__ == "__main__":
    for name, fn_name, _reserve, gate in bench.SECTIONS:
        if os.environ.get(gate, "1") == "0":
            continue
        t0 = time.time()
        out = bench._run_section(name, fn_name, timeout_s=1200)
        print(f"warm[{name}] {time.time() - t0:.1f}s -> {out}", flush=True)
