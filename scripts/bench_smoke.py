#!/usr/bin/env python
"""Dispatch-path perf smoke: one tiny PPO cycle run through BOTH train
paths (scanned lax.scan vs per-minibatch dispatch loop), printing one
JSON line with each train_s and the looped/scanned ratio.

CPU-friendly (tiny random model, byte tokenizer, zero egress) — run it
after touching the trainer dispatch path to see regressions without the
full bench: `python scripts/bench_smoke.py` (equivalently
`python bench.py --smoke`).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

if __name__ == "__main__":
    print(json.dumps({"metric": "ppo_smoke_train_ratio", **bench.bench_smoke()}))
