#!/usr/bin/env python
"""Docs <-> bench-artifact sync check (the r06-gap closer).

Round 6 reported engine numbers whose driver artifact
(``BENCH_r06.json``) was never recorded into the repo, so the claimed
recovery of the r05 ``train_s`` regression could not be confirmed from
checked-in data. ``bench.py --record`` now writes the artifact and the
docs/benchmarks.md trajectory row in ONE step; this check makes the
other direction structural:

1. every trajectory row that CLAIMS a number must cite a committed
   provenance artifact: its ``BENCH_rNN.json`` in the repo root, OR a
   run's ``telemetry.json`` snapshot (the flight recorder commits one
   alongside every checkpoint — ``trlx_tpu/obs/``; a committed copy
   must be provenance-stamped, i.e. parse with a ``provenance.run_id``)
   named in the row. A row explicitly marked ``*artifact missing*`` is
   an honest documented gap, not a violation; a row citing NEITHER
   fails loudly;
2. every ``BENCH_rNN.json`` artifact must have a trajectory row (an
   artifact the table never mentions is an unreported round).

Run standalone (exit 1 on problems) or via the tier-1 hook in
``tests/test_marker_audit.py``.
"""

from __future__ import annotations

import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cites_telemetry(repo: str, line: str) -> bool:
    """True when the trajectory row cites a committed telemetry.json
    that actually exists AND is provenance-stamped (parses with a
    ``provenance.run_id`` — the flight recorder's stamp; an empty file
    checked in to appease the checker is not provenance)."""
    for m in re.finditer(r"[\w./-]*telemetry[\w.-]*\.json", line, re.I):
        fp = os.path.join(repo, m.group(0))
        if not os.path.isfile(fp):
            continue
        try:
            with open(fp) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        prov = data.get("provenance") if isinstance(data, dict) else None
        if isinstance(prov, dict) and prov.get("run_id"):
            return True
    return False


def check(repo: str = REPO) -> list:
    """Return a list of problem strings (empty = in sync)."""
    problems = []
    doc_path = os.path.join(repo, "docs", "benchmarks.md")
    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError as e:
        return [f"{doc_path}: unreadable ({e})"]
    artifacts = {
        int(m.group(1))
        for e in os.listdir(repo)
        for m in [re.match(r"BENCH_r(\d+)\.json$", e)]
        if m
    }
    rows = {}
    for m in re.finditer(r"^\|\s*r(\d+)\s*\|([^|]*)\|.*$", doc, re.M):
        rows[int(m.group(1))] = (m.group(2).strip(), m.group(0))
    for nn, (cell, line) in sorted(rows.items()):
        claims_number = bool(re.search(r"\d", cell)) and "missing" not in cell
        if claims_number and nn not in artifacts and not (
            _cites_telemetry(repo, line)
        ):
            problems.append(
                f"docs/benchmarks.md trajectory row r{nn:02d} claims "
                f"{cell!r} but cites no committed artifact — record "
                f"BENCH_r{nn:02d}.json (bench.py --record), cite a "
                "committed provenance-stamped telemetry.json snapshot "
                "(the flight recorder writes one with every checkpoint), "
                "or mark the row '*artifact missing*'"
            )
    for nn in sorted(artifacts - set(rows)):
        problems.append(
            f"BENCH_r{nn:02d}.json exists but docs/benchmarks.md has no "
            f"r{nn:02d} trajectory row — bench.py --record appends it; "
            "add the row for hand-recorded artifacts"
        )
    # the r06 gap covered BOTH halves: multichip claims are made by
    # naming their artifact, so every MULTICHIP_rNN.json the docs cite
    # must be in the repo too (a citation on a line that admits the
    # artifact is missing is an honest documented gap)
    for m in re.finditer(r"MULTICHIP_r(\d+)\.json", doc):
        if os.path.isfile(os.path.join(repo, m.group(0))):
            continue
        # markdown wraps mid-sentence, so the honesty marker may sit on
        # a neighboring line — search a window around the citation
        window = doc[max(m.start() - 200, 0):m.end() + 200]
        if re.search(r"missing|not exist|never", window, re.I):
            continue
        problems.append(
            f"docs/benchmarks.md cites {m.group(0)} but the artifact "
            "is absent from the repo root — check it in or mark the "
            "citation '*artifact missing*'"
        )
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"FAIL  {p}")
    if not problems:
        print("OK    docs/benchmarks.md and BENCH_r*.json are in sync")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
