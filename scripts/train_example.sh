#!/bin/bash
# Single-host training entry (parity: /root/reference/scripts/
# accelerate_train_example.sh — there the launcher was `accelerate
# launch`; SPMD needs no launcher on one host).
#
# Usage: scripts/train_example.sh examples/ppo_sentiments.py '{"train.total_steps": 100}'
set -euo pipefail
cd "$(dirname "$0")/.."
python "${1:?usage: train_example.sh <script.py> [hparams-json]}" "${2:-{}}"
